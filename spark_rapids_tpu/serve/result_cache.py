"""Cross-query result cache: fingerprint + input-identity keyed, LRU
with a byte cap (``SRT_RESULT_CACHE``).

Dashboard-style serving repeats the same plan over the same inputs —
the ideal query does no device work at all.  A cache entry is keyed by
``(plan fingerprint, execution mode, input digest)`` where the input
digest hashes every batch's column names, dtypes, and host bytes
(:func:`input_digest`); only concretely re-hashable inputs (a Table, or
a list/tuple of Tables) are cacheable — iterator feeds are consumed by
execution and cannot be identity-checked, so they always miss without
being stored.  Values are whatever the executor returned (a Table or a
list of Tables); their size is accounted from host column bytes and the
LRU evicts oldest-first past the cap.

Hits/misses/evictions land on ``serve.result_cache.*`` counters and the
occupancy on the ``serve.result_cache.bytes`` gauge.  jax-free at
module load — digesting touches numpy only at call time.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple


def _digest_table(h, t) -> bool:
    """Fold one Table into hash ``h``; False when any column cannot be
    rendered to host bytes (then the whole input is uncacheable)."""
    try:
        h.update(str(t.num_rows).encode())
        # Mutation-generation stamp (Table.mark_mutated): an in-place
        # buffer write moves the table off generation 0, so its digest no
        # longer collides with the pristine bytes that were cached.
        h.update(str(getattr(t, "generation", 0)).encode())
        for name, col in t.items():
            vals, mask = col.to_numpy()
            h.update(name.encode())
            h.update(str(vals.dtype).encode())
            h.update(vals.tobytes())
            if mask is not None:
                h.update(mask.tobytes())
    except Exception:
        return False
    return True


def input_digest(inputs: Any) -> Optional[str]:
    """Identity digest of a query's input — a Table or a list/tuple of
    Tables — or None when the input cannot be safely re-hashed (an
    iterator/generator feed, or non-numpy-renderable columns)."""
    h = hashlib.sha256()
    if hasattr(inputs, "items") and hasattr(inputs, "num_rows"):
        return h.hexdigest() if _digest_table(h, inputs) else None
    if isinstance(inputs, (list, tuple)):
        for t in inputs:
            if not (hasattr(t, "items") and hasattr(t, "num_rows")):
                return None
            if not _digest_table(h, t):
                return None
        return h.hexdigest()
    return None


def result_nbytes(result: Any) -> int:
    """Host-byte size of an executor result (Table or list of Tables);
    0 when unmeasurable (the entry then costs nothing against the cap,
    which is safe because unmeasurable results are also undigestable
    and never stored)."""
    tables = result if isinstance(result, (list, tuple)) else [result]
    total = 0
    for t in tables:
        try:
            for _, col in t.items():
                vals, mask = col.to_numpy()
                total += vals.nbytes + (mask.nbytes if mask is not None
                                        else 0)
        except Exception:
            return 0
    return total


def contains_deleted(value: Any) -> bool:
    """True when any Table inside an executor result has had its device
    buffers donated away (``Table.is_deleted``) — e.g. the streaming
    executor donated a padded input mid-stream.  Such a value must never
    be cached: a later hit would hand out dead buffers."""
    tables = value if isinstance(value, (list, tuple)) else [value]
    for t in tables:
        is_deleted = getattr(t, "is_deleted", None)
        if callable(is_deleted) and is_deleted():
            return True
    return False


def _value_generations(value: Any) -> Tuple[int, ...]:
    """Generation stamps of every Table inside an executor result, in
    order — the snapshot taken at ``put`` and re-checked at ``get`` so a
    cached value mutated in place (Table.mark_mutated) is invalidated
    instead of served."""
    tables = value if isinstance(value, (list, tuple)) else [value]
    return tuple(getattr(t, "generation", 0) for t in tables)


class ResultCache:
    """Byte-capped LRU of executor results.  ``cap_bytes=None`` disables
    — every ``get`` misses without counting and ``put`` discards."""

    def __init__(self, cap_bytes: Optional[int] = None):
        self.cap_bytes = cap_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[Any, int, Tuple[int, ...]]]" \
            = OrderedDict()
        self._bytes = 0

    @property
    def enabled(self) -> bool:
        return self.cap_bytes is not None

    def get(self, key: Optional[Tuple]) -> Tuple[Any, bool]:
        """Returns ``(value, hit)``; an unkeyable input (key None) or a
        disabled cache always misses.  A stored value whose Tables moved
        off their put-time generation (mutated in place) is dropped and
        counted on ``serve.result_cache.stale_invalidations``."""
        if not self.enabled:
            return None, False
        from ..obs.metrics import counter, gauge
        with self._lock:
            if key is not None and key in self._entries:
                value, nbytes, gens = self._entries[key]
                if _value_generations(value) != gens:
                    del self._entries[key]
                    self._bytes -= nbytes
                    counter("serve.result_cache.stale_invalidations").inc()
                    counter("serve.result_cache.miss").inc()
                    gauge("serve.result_cache.bytes").set(self._bytes)
                    return None, False
                self._entries.move_to_end(key)
                counter("serve.result_cache.hit").inc()
                return value, True
            counter("serve.result_cache.miss").inc()
            return None, False

    def put(self, key: Optional[Tuple], value: Any) -> None:
        if not self.enabled or key is None:
            return
        if contains_deleted(value):
            from ..obs.metrics import counter
            counter("serve.cache.refused_deleted").inc()
            return
        nbytes = result_nbytes(value)
        if nbytes <= 0 or nbytes > self.cap_bytes:
            return
        from ..obs.metrics import counter, gauge
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes, _value_generations(value))
            self._bytes += nbytes
            while self._bytes > self.cap_bytes and self._entries:
                _, (_, dropped, _) = self._entries.popitem(last=False)
                self._bytes -= dropped
                counter("serve.result_cache.evictions").inc()
            gauge("serve.result_cache.bytes").set(self._bytes)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "cap_bytes": self.cap_bytes}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
