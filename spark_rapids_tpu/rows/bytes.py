"""Device byte-manipulation primitives for the row format.

The row blob is byte-addressed; TPU vector lanes are ≥8-bit but 64-bit types
are emulated (x64 rewriting).  Empirically (probed on TPU v5e):

  * ``bitcast_convert_type`` works for every width *except* float64 sources —
    the x64 rewriter has no lowering for 64-bit float bitcasts
    (``f64 -> u8``/``f64 -> i64`` fail to compile; ``u8 -> f64`` works).
  * int64 shifts/masks and f64 arithmetic (frexp et al.) are emulated fine.

So: ints/f32 use hardware bitcasts; f64 *packing* goes through an exact
software bit-extraction (:func:`f64_to_bits`) on backends that need it.
f64 *unpacking* uses the (working) u8→f64 bitcast everywhere.

This module replaces the reference CUDA kernels' per-thread byte ``switch``
(row_conversion.cu:128-156, :226-254) with whole-column vector ops, and its
``__ballot_sync``/``atomicOr_block`` validity bit handling
(row_conversion.cu:158-165, :255-272) with deterministic shift/mask packing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..dtypes import DType


def backend_has_native_f64_bitcast() -> bool:
    """True where f64→int bitcasts compile (CPU/GPU); False on TPU."""
    return jax.default_backend() != "tpu"


def f64_to_bits(x: jax.Array) -> jax.Array:
    """IEEE-754 bit pattern of float64 values, with no 64-bit bitcast.

    Used only where the native bitcast doesn't compile (TPU: the x64 rewriter
    emulates f64 and has no lowering for f64 bitcasts — nor for frexp /
    signbit / isnan, which lower through bitcasts).  Everything here is
    comparison + power-of-two multiplication (exact per IEEE) + integer ops:
    the exponent falls out of a branchless normalization of |x| into [1, 2)
    using steps of 2**±64 — constants safely inside float32's exponent range,
    because TPU "f64" is emulated with f32 pairs and larger constants degrade.

    Exact for ±0, normals and ±inf (to the precision the backend's f64
    arithmetic carries — full 52-bit on CPU, ~48-bit significands under TPU
    f32-pair emulation, which already bounds what a TPU-resident f64 column
    can hold).  Documented canonicalizations, both consistent with TPU
    numerics: NaN -> quiet NaN 0x7ff8000000000000 (sign preserved), and
    denormals -> ±0 (XLA flushes f64 denormals anyway).

    Returns int64 bit patterns.
    """
    one = jnp.float64(1.0)
    x = x.astype(jnp.float64)
    # Comparison-based classification (signbit/isnan/isinf all need bitcasts).
    sign = (x < 0) | ((x == 0) & (one / x < 0))          # catches -0.0
    ax = jnp.abs(x)
    is_nan = x != x
    is_inf = (ax * 0.5 == ax) & (ax > 0)
    # Branchless normalization of ax into [1, 2), tracking the exponent e so
    # that value == ax * 2**e.  Scale-up covers denormals (17*64 >= 1088 >
    # 1074); scale-down covers the top of the range (16*64 = 1024).
    e = jnp.zeros(x.shape, jnp.int64)
    up = jnp.float64(2.0**64)
    down = jnp.float64(2.0**-64)
    for _ in range(17):
        small = (ax > 0) & (ax < one)
        ax = jnp.where(small, ax * up, ax)
        e = e - jnp.where(small, 64, 0)
    for _ in range(16):
        big = ax >= up
        ax = jnp.where(big, ax * down, ax)
        e = e + jnp.where(big, 64, 0)
    for k in (32, 16, 8, 4, 2, 1):
        big = ax >= jnp.float64(2.0**k)
        ax = jnp.where(big, ax * jnp.float64(2.0**-k), ax)
        e = e + jnp.where(big, k, 0)
    # ax in [1, 2): mantissa = frac bits of ax * 2**52 (exactly an integer).
    biased = e + 1023
    mantissa = (ax * jnp.float64(2.0**52)).astype(jnp.int64) - (1 << 52)
    bits = (biased << 52) | mantissa
    bits = jnp.where((x == 0) | (biased <= 0), 0, bits)      # ±0 and denormals
    bits = jnp.where(is_inf, jnp.int64(0x7FF) << 52, bits)
    bits = jnp.where(is_nan, (jnp.int64(0x7FF) << 52) | (jnp.int64(1) << 51), bits)
    return bits | jnp.where(sign & ~is_nan, jnp.int64(np.int64(-2**63)), jnp.int64(0))


def to_bytes(data: jax.Array, dtype: DType) -> jax.Array:
    """Column values → little-endian bytes, shape ``(n, dtype.itemsize)``."""
    size = dtype.itemsize
    np_dt = dtype.np_dtype
    if size == 1:
        return data.view(jnp.uint8).reshape(-1, 1) if data.dtype != jnp.uint8 \
            else data.reshape(-1, 1)
    if np_dt == np.float64 and not backend_has_native_f64_bitcast():
        data = f64_to_bits(data)
    return lax.bitcast_convert_type(data, jnp.uint8)


def from_bytes(raw: jax.Array, dtype: DType) -> jax.Array:
    """Little-endian bytes ``(n, dtype.itemsize)`` → column values ``(n,)``."""
    target = dtype.jnp_dtype
    if dtype.itemsize == 1:
        return raw.reshape(-1).astype(target) if target != jnp.uint8 else raw.reshape(-1)
    return lax.bitcast_convert_type(raw, target)


def pack_validity_bytes(valid: jax.Array, num_bytes: int) -> jax.Array:
    """Pack a bool matrix ``(n, num_fields)`` into row-format validity bytes.

    Bit ``f % 8`` of byte ``f // 8`` is set iff field ``f`` is valid — the row
    tail contract (row_conversion.cu:159-161 reads it back the same way).
    Bits beyond ``num_fields`` are zero (deterministic, unlike the reference,
    which leaves them as garbage shared-memory residue).
    """
    n, num_fields = valid.shape
    padded = jnp.zeros((n, num_bytes * 8), dtype=jnp.uint8)
    padded = padded.at[:, :num_fields].set(valid.astype(jnp.uint8))
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))
    groups = padded.reshape(n, num_bytes, 8).astype(jnp.uint32)
    return jnp.sum(groups * weights, axis=-1).astype(jnp.uint8)


def unpack_validity_bytes(raw: jax.Array, num_fields: int) -> jax.Array:
    """Inverse of :func:`pack_validity_bytes`; returns bool ``(n, num_fields)``."""
    byte_idx = np.arange(num_fields) // 8
    shifts = jnp.asarray(np.arange(num_fields) % 8, dtype=jnp.uint8)
    per_field = raw[:, byte_idx]                  # (n, num_fields)
    return ((per_field >> shifts) & 1).astype(jnp.bool_)
