"""Adaptive plan optimizer tests (exec/optimize.py).

Two layers:

1. **Rule units** — each rewrite rule applied to hand-built plans, checking
   the rewritten step list directly (no execution needed).
2. **Bit-identity oracles** — the same plan run with ``SRT_PLAN_OPT=0``
   (the unoptimized oracle) and with the optimizer on, across all
   executors (run / stream / dist / dist_stream), including null keys,
   bucket-boundary sizes, and the faulted recovery-split path.  Results
   must match exactly — the optimizer's contract is *bit*-identity, not
   approximate equality.
"""

import json

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.exec.expr import BinOp
from spark_rapids_tpu.exec.optimize import (live_input_names, optimize,
                                            source_plan)
from spark_rapids_tpu.exec.plan import (FilterStep, JoinShuffledStep,
                                        JoinStep, ProjectStep, SortStep,
                                        TopKStep)
from spark_rapids_tpu.parallel import make_flat_mesh, shard_table


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Optimizer defaults (on, all rules), no metrics, no history."""
    for var in ("SRT_PLAN_OPT", "SRT_PLAN_OPT_RULES", "SRT_METRICS",
                "SRT_METRICS_HISTORY", "SRT_FAULT"):
        monkeypatch.delenv(var, raising=False)
    from spark_rapids_tpu.resilience import reset_faults
    reset_faults()
    yield


@pytest.fixture(scope="module")
def mesh():
    return make_flat_mesh()


def _table(n=1000, seed=0, null_keys=False):
    r = np.random.default_rng(seed)
    return Table([
        ("k", Column.from_numpy(
            r.integers(0, 8, n).astype(np.int64),
            validity=(r.random(n) > 0.15) if null_keys else None)),
        ("v", Column.from_numpy(r.integers(-50, 100, n).astype(np.int64),
                                validity=r.random(n) > 0.2)),
        ("f", Column.from_numpy(r.normal(size=n))),
        ("unused", Column.from_numpy(r.integers(0, 5, n).astype(np.int64))),
    ])


def _oracle_vs_optimized(p, runner, monkeypatch):
    """Run ``runner(p)`` with the optimizer off, then on; both results."""
    monkeypatch.setenv("SRT_PLAN_OPT", "0")
    want = runner(p)
    monkeypatch.delenv("SRT_PLAN_OPT")
    got = runner(p)
    return want, got


# ---------------------------------------------------------------------------
# 1. rule units
# ---------------------------------------------------------------------------

class TestRules:
    def test_pushdown_over_rename_project(self):
        p = (plan().select(("kk", col("k")), ("v", col("v")))
             .filter(col("kk") > 3))
        o = optimize(p)
        assert o.opt.rewrites.get("pushdown") == 1
        # Prune inserts a leading select; the hoisted filter references
        # the SOURCE name k below the rename.
        flt = next(s for s in o.steps if isinstance(s, FilterStep))
        idx = o.steps.index(flt)
        assert all(not isinstance(s, FilterStep)
                   or o.steps.index(s) >= idx for s in o.steps)
        from spark_rapids_tpu.exec.expr import references
        assert references(flt.pred) == {"k"}

    def test_pushdown_blocked_by_computed_column(self):
        p = plan().with_columns(z=col("v") * 2).filter(col("z") > 0)
        o = optimize(p)
        assert "pushdown" not in o.opt.rewrites

    def test_pushdown_into_union_branch(self):
        t = _table(64, seed=1)
        p = plan().union_all(t).filter(col("v") > 0)
        o = optimize(p)
        assert o.opt.rewrites.get("pushdown") == 1
        union = next(s for s in o.steps if hasattr(s, "plan"))
        assert isinstance(union.plan.steps[-1], FilterStep)

    def test_reorder_fuses_filter_run(self):
        p = plan().filter(col("v") > 0).filter(col("k") < 5)
        o = optimize(p)
        filters = [s for s in o.steps if isinstance(s, FilterStep)]
        assert len(filters) == 1
        assert isinstance(filters[0].pred, BinOp)
        assert o.opt.rewrites.get("reorder", 0) >= 1

    def test_analyze_mode_keeps_conjuncts_split(self):
        p = plan().filter((col("v") > 0) & (col("k") < 5))
        o = optimize(p, mode="analyze")
        filters = [s for s in o.steps if isinstance(s, FilterStep)]
        assert len(filters) == 2

    def test_reorder_orders_by_history_selectivity(self, monkeypatch):
        from spark_rapids_tpu.exec.expr import render
        from spark_rapids_tpu.obs import history
        rec = {"steps": [
            {"kind": "Filter", "rows_in": 100, "rows_out": 90,
             "describe": f"Filter[{render(col('v') > 0)}] -> selection mask"},
            {"kind": "Filter", "rows_in": 90, "rows_out": 3,
             "describe": f"Filter[{render(col('k') < 5)}] -> selection mask"},
        ]}
        monkeypatch.setattr(history, "lookup_latest", lambda *a, **k: rec)
        p = plan().filter(col("v") > 0).filter(col("k") < 5)
        o = optimize(p)
        assert o.opt.history_informed
        flt = next(s for s in o.steps if isinstance(s, FilterStep))
        # Most selective conjunct (k < 5, 3%) must now lead the AND.
        assert render(flt.pred).startswith("((k < 5)")

    def test_topk_fuses_sort_limit(self):
        p = plan().groupby_agg(["k"], [("v", "sum", "s")]) \
                  .sort_by(["s"], ascending=[False]).limit(10)
        o = optimize(p)
        assert isinstance(o.steps[-1], TopKStep)
        assert o.steps[-1].k == 10
        assert not any(isinstance(s, SortStep) for s in o.steps)

    def test_prune_inserts_leading_narrow_select(self):
        p = plan().filter(col("v") > 0).groupby_agg(
            ["k"], [("v", "sum", "s")])
        o = optimize(p)
        lead = o.steps[0]
        assert isinstance(lead, ProjectStep) and lead.narrow
        assert {nm for nm, _ in lead.cols} == {"k", "v"}
        assert live_input_names(o) == ("k", "v")

    def test_prune_never_narrows_passthrough_output(self):
        # No projection/groupby caps the schema: every input column may
        # reach the output, so nothing can be pruned.
        p = plan().filter(col("v") > 0)
        o = optimize(p)
        assert "prune" not in o.opt.rewrites

    def test_disabled_returns_plan_unchanged(self, monkeypatch):
        monkeypatch.setenv("SRT_PLAN_OPT", "0")
        p = plan().filter(col("v") > 0).sort_by(["k"]).limit(3)
        assert optimize(p) is p
        assert getattr(p, "opt", None) is None

    def test_rule_subset_env(self, monkeypatch):
        monkeypatch.setenv("SRT_PLAN_OPT_RULES", "topk")
        p = plan().filter(col("v") > 0).filter(col("k") < 5) \
                  .sort_by(["k"]).limit(3)
        o = optimize(p)
        assert set(o.opt.rewrites) == {"topk"}
        # both filters survive un-fused
        assert sum(isinstance(s, FilterStep) for s in o.steps) == 2

    def test_reentry_guard(self):
        p = plan().sort_by(["k"]).limit(3)
        o = optimize(p)
        assert optimize(o) is o
        assert source_plan(o) is p
        assert source_plan(p) is p

    def test_original_plan_never_mutated(self):
        p = plan().filter(col("v") > 0).sort_by(["k"]).limit(3)
        steps = p.steps
        o = optimize(p)
        assert o is not p and p.steps == steps
        assert getattr(p, "opt", None) is None


class TestJoinRule:
    def _dim(self, rows=6):
        return Table([
            ("dk", Column.from_numpy(np.arange(rows, dtype=np.int64))),
            ("w", Column.from_numpy(
                np.arange(rows, dtype=np.int64) * 10)),
        ])

    def _plan(self, dim):
        return (plan()
                .join_shuffled(dim, left_on="k", right_on="dk",
                               how="inner")
                .groupby_agg(["k"], [("w", "sum", "ws"),
                                     ("v", "count", "n")]))

    def test_small_unique_build_becomes_broadcast(self):
        p = self._plan(self._dim())
        o = optimize(p, mode="dist", probe_rows=100000, mesh_size=8,
                     probe_table=_table(64))
        assert o.opt.rewrites.get("join") == 1
        assert any(isinstance(s, JoinStep) for s in o.steps)
        assert not any(isinstance(s, JoinShuffledStep) for s in o.steps)

    def test_join_rule_only_fires_in_dist_mode(self):
        p = self._plan(self._dim())
        o = optimize(p, probe_rows=100000, mesh_size=8)
        assert "join" not in o.opt.rewrites

    def test_duplicate_build_keys_block_rewrite(self):
        dim = Table([
            ("dk", Column.from_numpy(
                np.array([0, 1, 1, 2], dtype=np.int64))),
            ("w", Column.from_numpy(np.arange(4, dtype=np.int64))),
        ])
        o = optimize(self._plan(dim), mode="dist", probe_rows=100000,
                     mesh_size=8, probe_table=_table(64))
        assert "join" not in o.opt.rewrites

    def test_cost_model_keeps_shuffle_for_small_probe(self):
        # Replicating the build on every shard costs more than shuffling
        # a probe this small: build_rows * shards >= probe + build_rows.
        p = self._plan(self._dim(100))
        o = optimize(p, mode="dist", probe_rows=50, mesh_size=8,
                     probe_table=_table(64))
        assert "join" not in o.opt.rewrites

    def test_order_sensitive_agg_blocks_rewrite(self):
        dim = self._dim()
        p = (plan()
             .join_shuffled(dim, left_on="k", right_on="dk", how="inner")
             .groupby_agg(["k"], [("f", "sum", "fs")]))  # float sum
        o = optimize(p, mode="dist", probe_rows=100000, mesh_size=8,
                     probe_table=_table(64))
        assert "join" not in o.opt.rewrites

    def test_history_probe_cardinality_marks_informed(self, monkeypatch):
        from spark_rapids_tpu.obs import history
        rec = {"input": {"rows": 500000},
               "steps": [{"kind": "Filter", "rows_in": 10, "rows_out": 1,
                          "describe": "x"}]}
        monkeypatch.setattr(history, "lookup_latest", lambda *a, **k: rec)
        p = self._plan(self._dim())
        o = optimize(p, mode="dist", probe_rows=None, mesh_size=8,
                     probe_table=_table(64))
        assert o.opt.rewrites.get("join") == 1
        assert o.opt.history_informed


# ---------------------------------------------------------------------------
# 2. config / plan / history satellites
# ---------------------------------------------------------------------------

class TestConfig:
    def test_default_rules(self):
        from spark_rapids_tpu.config import (PLAN_OPT_RULE_NAMES, plan_opt,
                                             plan_opt_rules)
        assert plan_opt() is True
        assert plan_opt_rules() == PLAN_OPT_RULE_NAMES

    def test_rules_parse_dedup_and_order(self, monkeypatch):
        from spark_rapids_tpu.config import plan_opt_rules
        monkeypatch.setenv("SRT_PLAN_OPT_RULES", " Topk, prune,topk ,")
        assert plan_opt_rules() == ("topk", "prune")

    def test_unknown_rule_raises(self, monkeypatch):
        from spark_rapids_tpu.config import plan_opt_rules
        monkeypatch.setenv("SRT_PLAN_OPT_RULES", "topk,warp")
        with pytest.raises(ValueError, match="warp"):
            plan_opt_rules()

    def test_plan_opt_off_spellings(self, monkeypatch):
        from spark_rapids_tpu.config import plan_opt
        for off in ("0", "off", "false", "no", ""):
            monkeypatch.setenv("SRT_PLAN_OPT", off)
            assert plan_opt() is False
        monkeypatch.setenv("SRT_PLAN_OPT", "1")
        assert plan_opt() is True

    def test_optimize_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="mode"):
            optimize(plan(), mode="warp")


class TestScanPredicates:
    def test_sees_through_rename_select(self):
        p = (plan().select(("year", col("d_year")), ("v", col("v")))
             .filter(col("year").eq(2001)))
        (leaf,) = p.scan_predicates()
        assert leaf.column == "d_year" and leaf.op == "eq" \
            and leaf.value == 2001

    def test_sees_through_passthrough_with_columns(self):
        p = plan().with_columns(z=col("v") * 2).filter(col("k") > 3)
        (leaf,) = p.scan_predicates()
        assert leaf.column == "k"

    def test_computed_column_predicate_dropped(self):
        p = plan().with_columns(z=col("v") * 2).filter(col("z") > 3)
        assert p.scan_predicates() == ()

    def test_direct_filter_unchanged(self):
        p = plan().filter(col("k") > 3)
        (leaf,) = p.scan_predicates()
        assert leaf.column == "k" and leaf.op == "gt"


class TestHistoryLookup:
    def test_missing_file_answers_none(self, tmp_path):
        from spark_rapids_tpu.obs.history import lookup_latest
        assert lookup_latest("beef" * 4,
                             path=str(tmp_path / "nope.jsonl")) is None

    def test_unmeasured_records_skipped(self, tmp_path):
        from spark_rapids_tpu.obs.history import lookup_latest
        path = tmp_path / "h.jsonl"
        fp = "beef" * 4
        lines = [
            json.dumps({"fingerprint": fp, "tag": "old", "steps": [
                {"kind": "Filter", "rows_in": 10, "rows_out": 4}]}),
            json.dumps({"fingerprint": fp, "tag": "new", "steps": [
                {"kind": "Filter", "rows_in": -1, "rows_out": -1}]}),
        ]
        path.write_text("\n".join(lines) + "\n")
        rec = lookup_latest(fp, path=str(path))
        assert rec is not None and rec["tag"] == "old"

    def test_corrupt_lines_skipped(self, tmp_path):
        from spark_rapids_tpu.obs.history import lookup_latest
        path = tmp_path / "h.jsonl"
        fp = "beef" * 4
        good = json.dumps({"fingerprint": fp, "steps": [
            {"kind": "Filter", "rows_in": 10, "rows_out": 4}]})
        path.write_text('{"torn": \n' + good + "\n[1,2]\n")
        assert lookup_latest(fp, path=str(path)) is not None

    def test_other_fingerprints_ignored(self, tmp_path):
        from spark_rapids_tpu.obs.history import lookup_latest
        path = tmp_path / "h.jsonl"
        path.write_text(json.dumps({"fingerprint": "cafe" * 4, "steps": [
            {"kind": "Filter", "rows_in": 10, "rows_out": 4}]}) + "\n")
        assert lookup_latest("beef" * 4, path=str(path)) is None


# ---------------------------------------------------------------------------
# 3. bit-identity oracles across all executors
# ---------------------------------------------------------------------------

def _query():
    return (plan().filter(col("v") > 0)
            .with_columns(v2=col("v") * 2)
            .filter(col("k") < 6)
            .groupby_agg(["k"], [("v2", "sum", "s"), ("v", "count", "n")],
                         domains={"k": (0, 7)})
            .sort_by(["s"], ascending=[False]).limit(5))


class TestOracleParity:
    @pytest.mark.parametrize("n", [64, 65, 150, 1000])
    def test_run_matches_oracle_at_bucket_boundaries(self, n, monkeypatch):
        t = _table(n, seed=n)
        want, got = _oracle_vs_optimized(
            _query(), lambda p: p.run(t).to_pydict(), monkeypatch)
        assert got == want

    def test_run_with_null_keys(self, monkeypatch):
        t = _table(500, seed=3, null_keys=True)
        want, got = _oracle_vs_optimized(
            _query(), lambda p: p.run(t).to_pydict(), monkeypatch)
        assert got == want

    def test_row_local_with_sort_and_strings_untouched(self, monkeypatch):
        # Sort not followed by limit must NOT become top-k.
        t = _table(200, seed=4)
        p = plan().filter(col("v") > 0).sort_by(["k", "v"])
        o = optimize(p)
        assert not any(isinstance(s, TopKStep) for s in o.steps)
        want, got = _oracle_vs_optimized(
            p, lambda q: q.run(t).to_pydict(), monkeypatch)
        assert got == want

    def test_stream_per_batch_matches_oracle(self, monkeypatch):
        batches = [_table(97, seed=i) for i in range(4)]
        p = plan().filter(col("v") > 0).with_columns(v2=col("v") + 1)

        def runner(q):
            return [t.to_pydict()
                    for t in q.run_stream(list(batches), combine=False)]
        want, got = _oracle_vs_optimized(p, runner, monkeypatch)
        assert got == want

    def test_stream_combine_matches_oracle(self, monkeypatch):
        batches = [_table(97, seed=i) for i in range(4)]
        p = (plan().filter(col("v") > 0)
             .groupby_agg(["k"], [("v", "sum", "s")],
                          domains={"k": (0, 7)}))

        def runner(q):
            (out,) = list(q.run_stream(list(batches), combine=True))
            return out.to_pydict()
        want, got = _oracle_vs_optimized(p, runner, monkeypatch)
        assert got == want

    def test_dist_matches_oracle(self, mesh, monkeypatch):
        t = _table(803, seed=5, null_keys=True)
        p = (plan().filter(col("v") > 0)
             .groupby_agg(["k"], [("v", "sum", "s"), ("v", "count", "n")],
                          domains={"k": (0, 7)})
             .sort_by(["k"]))

        def runner(q):
            return q.run_dist(shard_table(t, mesh), mesh).to_pydict()
        want, got = _oracle_vs_optimized(p, runner, monkeypatch)
        assert got == want

    def test_dist_broadcast_rewrite_matches_oracle(self, mesh, monkeypatch):
        t = _table(900, seed=6)
        dim = Table([
            ("dk", Column.from_numpy(np.arange(8, dtype=np.int64))),
            ("w", Column.from_numpy(
                np.arange(8, dtype=np.int64) * 7))])
        p = (plan()
             .join_shuffled(dim, left_on="k", right_on="dk", how="inner")
             .groupby_agg(["k"], [("w", "sum", "ws"),
                                  ("v", "count", "n")],
                          domains={"k": (0, 7)})
             .sort_by(["k"]))

        def runner(q):
            return q.run_dist(shard_table(t, mesh), mesh).to_pydict()
        want, got = _oracle_vs_optimized(p, runner, monkeypatch)
        assert got == want

    def test_dist_stream_matches_oracle(self, mesh, monkeypatch):
        batches = [_table(97, seed=10 + i) for i in range(3)]
        p = (plan().filter(col("v") > 0)
             .groupby_agg(["k"], [("v", "sum", "s")],
                          domains={"k": (0, 7)}))

        def runner(q):
            (out,) = list(q.run_dist_stream(list(batches), mesh,
                                            combine=True))
            return out.to_pydict()
        want, got = _oracle_vs_optimized(p, runner, monkeypatch)
        assert got == want

    def test_faulted_recovery_split_with_optimizer_on(self, monkeypatch):
        from spark_rapids_tpu.resilience import recovery_stats, reset_faults
        t = _table(150, seed=7)
        p = plan().filter(col("v") > 0).with_columns(v2=col("v") * 3)
        monkeypatch.setenv("SRT_PLAN_OPT", "0")
        oracle = p.run(t).to_pydict()
        monkeypatch.delenv("SRT_PLAN_OPT")
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
        monkeypatch.setenv("SRT_FAULT", "oom:dispatch:2")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run(t).to_pydict() == oracle
        assert recovery_stats().delta(before)["splits"] >= 1


# ---------------------------------------------------------------------------
# 4. telemetry integration: opt block, pruned columns, history feedback
# ---------------------------------------------------------------------------

class TestTelemetry:
    def test_opt_block_and_pruned_columns(self, monkeypatch):
        from spark_rapids_tpu.obs import last_query_metrics
        monkeypatch.setenv("SRT_METRICS", "1")
        t = _table(300, seed=8)
        _query().run(t)
        d = last_query_metrics().to_dict()
        assert d["opt"]["enabled"] is True
        assert d["opt"]["rewrites"]
        assert d["opt"]["steps_before"] >= d["opt"]["steps_after"] - 1
        # 'unused' and 'f' never feed the aggregation: both pruned
        # before bind.
        assert d["opt"]["pruned_columns"] >= 2

    def test_oracle_metrics_report_disabled(self, monkeypatch):
        from spark_rapids_tpu.obs import last_query_metrics
        monkeypatch.setenv("SRT_METRICS", "1")
        monkeypatch.setenv("SRT_PLAN_OPT", "0")
        _query().run(_table(300, seed=8))
        d = last_query_metrics().to_dict()
        assert d["opt"]["enabled"] is False
        assert d["opt"]["rewrites"] == {}

    def test_history_warmed_run_is_history_informed(self, tmp_path,
                                                    monkeypatch):
        from spark_rapids_tpu.obs import last_query_metrics
        monkeypatch.setenv("SRT_METRICS", "1")
        monkeypatch.setenv("SRT_METRICS_HISTORY",
                           str(tmp_path / "hist.jsonl"))
        t = _table(600, seed=9)
        # Wide-then-narrow conjunct order: v > -1000 keeps ~every row,
        # k == 0 keeps ~1/8 — the history-fed reorder must swap them.
        p = (plan().filter(col("v") > -1000).filter(col("k").eq(0))
             .groupby_agg(["k"], [("v", "sum", "s")],
                          domains={"k": (0, 7)}))
        # Cold analyze run: conjuncts stay split, each one's observed
        # selectivity lands in the history file.
        p.explain_analyze(t)
        cold = last_query_metrics().to_dict()
        assert cold["opt"]["enabled"] and not cold["opt"]["history_informed"]
        # Warm run: reorder reads the history back and swaps the
        # conjuncts; the opt block records the feedback loop closing.
        out = p.run(t)
        warm = last_query_metrics().to_dict()
        assert warm["opt"]["history_informed"] is True
        assert warm["opt"]["rewrites"].get("reorder", 0) >= 1
        monkeypatch.setenv("SRT_PLAN_OPT", "0")
        assert p.run(t).to_pydict() == out.to_pydict()

    def test_explain_shows_before_after_diff(self):
        t = _table(64, seed=11)
        text = _query().explain(t)
        assert "== Optimizer ==" in text
        assert "- Sort[s]" in text and "+ TopK[s k=5]" in text
