"""Whole-column reductions (cudf ``reduce`` surface)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..column import Column


def _valid_data(col: Column, identity):
    if col.validity is None:
        return col.data, col.size
    data = jnp.where(col.validity, col.data, col.data.dtype.type(identity))
    return data, int(jnp.sum(col.validity))


def sum(col: Column):  # noqa: A001 - cudf-style name
    """Sum of valid values.  Returns the *logical* value: decimals apply
    their 10**scale factor (as a float)."""
    data, n = _valid_data(col, 0)
    if n == 0:
        return None
    from .groupby import _sum_dtype
    total = jnp.sum(data.astype(_sum_dtype(col.dtype).jnp_dtype)).item()
    if col.dtype.is_decimal:
        return total * (10.0 ** col.dtype.scale)
    return total


def count(col: Column) -> int:
    return col.size - col.null_count()


def minimum(col: Column):
    if col.dtype.is_floating:
        ident = np.inf
    else:
        ident = np.iinfo(col.dtype.np_dtype).max
    data, n = _valid_data(col, ident)
    if n == 0:
        return None
    return jnp.min(data).item()


def maximum(col: Column):
    if col.dtype.is_floating:
        ident = -np.inf
    else:
        ident = np.iinfo(col.dtype.np_dtype).min
    data, n = _valid_data(col, ident)
    if n == 0:
        return None
    return jnp.max(data).item()


def mean(col: Column):
    data, n = _valid_data(col, 0)
    if n == 0:
        return None
    scale = 10.0 ** col.dtype.scale if col.dtype.is_decimal else 1.0
    return (jnp.sum(data.astype(jnp.float64)) * scale / n).item()
