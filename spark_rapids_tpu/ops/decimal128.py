"""128-bit decimal limb arithmetic.

TPU has no 128-bit scalar type, so DECIMAL128 columns are ``(n, 2)``
uint64 arrays of little-endian (lo, hi) words in two's complement — the
exact byte layout of Arrow / cudf ``fixed_point<__int128_t>`` values, so
interop is a view, not a conversion.  The reference's bridge reconstructs
decimal types from (type-id, scale) wire pairs (RowConversionJni.cpp:56-61);
Spark's default decimal (38, 18) is this type.

Everything here is vectorized limb arithmetic on u64 (or u32 sub-limb)
lanes — adds with carry, compares via (hi signed, lo unsigned)
lexicographic order, and base-10 rescaling:

* scale DOWN (multiply by 10^k): schoolbook 64x64 multiply split into
  32-bit half-limbs so partial products fit u64;
* scale UP (divide by 10^k): long division over four 32-bit limbs by a
  divisor < 2^30, applied in <= 10^9 chunks, truncating toward zero
  (cudf ``fixed_point::rescaled`` semantics).

Key ordering everywhere (sort / group-by / join) reduces a decimal128 to
TWO ordinary key operands — hi as signed int64, lo as unsigned — which
compare identically to the 128-bit signed value; the engine's multi-key
machinery handles the rest (see ops.common.grouping_columns).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..column import Column
from ..dtypes import DType, INT64, UINT64

_U64 = jnp.uint64
_MASK32 = (1 << 32) - 1


def split_words(data: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(n, 2) words -> (lo u64, hi u64)."""
    return data[:, 0], data[:, 1]


def join_words(lo: jax.Array, hi: jax.Array) -> jax.Array:
    return jnp.stack([lo.astype(_U64), hi.astype(_U64)], axis=1)


def key_columns(col: Column) -> list[Column]:
    """Order/equality-preserving expansion into two ordinary key columns:
    (hi as SIGNED int64, lo as unsigned) — lexicographic comparison on the
    pair equals signed 128-bit numeric comparison."""
    lo, hi = split_words(col.data)
    hi_signed = lax.bitcast_convert_type(hi, jnp.int64)
    return [
        Column(data=hi_signed, validity=col.validity, dtype=INT64),
        Column(data=lo, validity=col.validity, dtype=UINT64),
    ]


# ---------------------------------------------------------------------------
# add / negate / compare
# ---------------------------------------------------------------------------

def negate(data: jax.Array) -> jax.Array:
    """Two's-complement 128-bit negation: ~x + 1.  The +1 carries into
    the high word exactly when the low word is zero (~lo + 1 wraps)."""
    lo, hi = split_words(data)
    nlo = (~lo) + _U64(1)
    nhi = (~hi) + jnp.where(lo == 0, _U64(1), _U64(0))
    return join_words(nlo, nhi)


def add(a: jax.Array, b: jax.Array) -> jax.Array:
    """128-bit wrapping add."""
    alo, ahi = split_words(a)
    blo, bhi = split_words(b)
    lo = alo + blo
    carry = (lo < alo).astype(_U64)
    hi = ahi + bhi + carry
    return join_words(lo, hi)


def is_negative(data: jax.Array) -> jax.Array:
    _, hi = split_words(data)
    return lax.bitcast_convert_type(hi, jnp.int64) < 0


def compare(a: jax.Array, b: jax.Array) -> jax.Array:
    """Signed comparison: -1 / 0 / +1 as int32."""
    alo, ahi = split_words(a)
    blo, bhi = split_words(b)
    ahs = lax.bitcast_convert_type(ahi, jnp.int64)
    bhs = lax.bitcast_convert_type(bhi, jnp.int64)
    hi_lt, hi_gt = ahs < bhs, ahs > bhs
    lo_lt, lo_gt = alo < blo, alo > blo
    lt = hi_lt | (~hi_gt & lo_lt)
    gt = hi_gt | (~hi_lt & lo_gt)
    return jnp.where(lt, jnp.int32(-1), jnp.where(gt, jnp.int32(1),
                                                  jnp.int32(0)))


# ---------------------------------------------------------------------------
# widen / narrow
# ---------------------------------------------------------------------------

def from_int64(v: jax.Array) -> jax.Array:
    """Sign-extend int64 unscaled values to 128-bit words."""
    lo = lax.bitcast_convert_type(v.astype(jnp.int64), _U64)
    hi = jnp.where(v < 0, _U64(0xFFFFFFFFFFFFFFFF), _U64(0))
    return join_words(lo, hi)


def to_int64(data: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Narrow to int64: (values, in_range mask)."""
    lo, hi = split_words(data)
    v = lax.bitcast_convert_type(lo, jnp.int64)
    # In range iff hi is the sign extension of lo's top bit.
    expect_hi = jnp.where(v < 0, _U64(0xFFFFFFFFFFFFFFFF), _U64(0))
    return v, hi == expect_hi


def to_float64(data: jax.Array) -> jax.Array:
    lo, hi = split_words(data)
    his = lax.bitcast_convert_type(hi, jnp.int64)
    return his.astype(jnp.float64) * jnp.float64(2.0 ** 64) \
        + lo.astype(jnp.float64)


# ---------------------------------------------------------------------------
# base-10 rescale
# ---------------------------------------------------------------------------

def _mul_u64(a: jax.Array, b_const: int):
    """a (u64) * b (python int < 2^64) -> (lo u64, carry u64) via 32-bit
    half-limb schoolbook multiply."""
    a_lo = a & _U64(_MASK32)
    a_hi = a >> _U64(32)
    b_lo = b_const & _MASK32
    b_hi = b_const >> 32
    p0 = a_lo * _U64(b_lo)                      # <= 2^64 - 2^33 + 1: fits
    p1a = a_lo * _U64(b_hi)
    p1b = a_hi * _U64(b_lo)
    p2 = a_hi * _U64(b_hi)
    mid = p1a + (p0 >> _U64(32))
    mid_carry = (mid < p1a).astype(_U64)
    mid2 = mid + p1b
    mid_carry = mid_carry + (mid2 < mid).astype(_U64)
    lo = (p0 & _U64(_MASK32)) | (mid2 << _U64(32))
    hi = p2 + (mid2 >> _U64(32)) + (mid_carry << _U64(32))
    return lo, hi


def mul_pow10(data: jax.Array, k: int) -> jax.Array:
    """Multiply by 10^k (k >= 0), wrapping at 128 bits (cudf rescale
    contract: overflow is the caller's precision responsibility)."""
    out = data
    while k > 0:
        step = min(k, 19)                       # 10^19 < 2^64
        m = 10 ** step
        lo, hi = split_words(out)
        new_lo, carry = _mul_u64(lo, m)
        hi_lo, _ = _mul_u64(hi, m)
        out = join_words(new_lo, hi_lo + carry)
        k -= step
    return out


def _div_small(data: jax.Array, d: int) -> jax.Array:
    """Unsigned 128-bit // d for 0 < d < 2^30, via four 32-bit limbs."""
    lo, hi = split_words(data)
    limbs = [hi >> _U64(32), hi & _U64(_MASK32),
             lo >> _U64(32), lo & _U64(_MASK32)]      # most significant first
    dd = jnp.int64(d)
    r = jnp.zeros_like(lo, jnp.int64)
    q = []
    for limb in limbs:
        cur = (r << jnp.int64(32)) | limb.astype(jnp.int64)
        q.append((cur // dd).astype(_U64))
        r = cur % dd
    out_hi = (q[0] << _U64(32)) | q[1]
    out_lo = (q[2] << _U64(32)) | q[3]
    return join_words(out_lo, out_hi)


def div_pow10(data: jax.Array, k: int) -> jax.Array:
    """Signed division by 10^k (k >= 0), truncating toward zero."""
    if k == 0:
        return data
    neg = is_negative(data)
    mag = jnp.where(neg[:, None], negate(data), data)
    while k > 0:
        step = min(k, 9)                        # 10^9 < 2^30
        mag = _div_small(mag, 10 ** step)
        k -= step
    return jnp.where(neg[:, None], negate(mag), mag)


def rescale(data: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    """Move between base-10 scales (value = unscaled * 10**scale)."""
    diff = from_scale - to_scale
    if diff == 0:
        return data
    if diff > 0:
        return mul_pow10(data, diff)
    return div_pow10(data, -diff)


# ---------------------------------------------------------------------------
# casts (wired from ops.cast)
# ---------------------------------------------------------------------------

def cast_to_d128(col: Column, to: DType) -> Column:
    """numeric/decimal -> decimal128."""
    src = col.dtype
    if src.is_two_word:
        data = rescale(col.data, src.scale, to.scale)
    elif src.is_floating:
        scaled = col.data.astype(jnp.float64) * (10.0 ** -to.scale)
        scaled = jnp.trunc(scaled)
        # f64 has 53 mantissa bits; route through int64 (documented
        # precision limit of float->decimal128, same as any f64 source).
        data = from_int64(scaled.astype(jnp.int64))
    else:
        v = col.data.astype(jnp.int64)
        data = rescale(from_int64(v), src.scale, to.scale)
    return Column(data=data, validity=col.validity, dtype=to)


def cast_from_d128(col: Column, to: DType) -> Column:
    """decimal128 -> numeric/decimal."""
    src = col.dtype
    if to.is_two_word:
        return cast_to_d128(col, to)
    if to.is_floating:
        data = to_float64(col.data) * (10.0 ** src.scale)
        return Column(data=data.astype(to.jnp_dtype), validity=col.validity,
                      dtype=to)
    target_scale = to.scale if to.is_decimal else 0
    rescaled = rescale(col.data, src.scale, target_scale)
    v, ok = to_int64(rescaled)
    validity = col.validity
    # Out-of-range narrows become nulls (cudf overflow is UB; nulling is
    # the defined, testable behavior here).
    validity = ok if validity is None else (validity & ok)
    return Column(data=v.astype(to.jnp_dtype), validity=validity, dtype=to)
