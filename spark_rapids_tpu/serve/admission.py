"""Admission control and per-query HBM budgeting for the serving layer.

The OOM recovery ladder (resilience/recovery.py) rescues a query that
over-committed device memory AFTER the allocation failed — evict,
backoff, retry, split.  Under concurrent serving that is the wrong
steady state: two heavy queries admitted together would spend their
time fighting the ladder.  This module moves the decision BEFORE
dispatch: each submission's peak-HBM claim is estimated from the
per-fingerprint cost-ledger history (``cost.hbm.peak_bytes`` of the
most recent measured run, obs/history.py), and the controller only lets
a query start once the sum of running claims plus its own fits the
budget (``SRT_SERVE_HBM_BUDGET``).  Queries that would over-commit wait
in the run queue; a query whose own estimate exceeds the entire budget
can never run and is rejected outright (counted on
``serve.admission.rejected``).  Cold fingerprints (no history) claim
zero — they admit freely and the ladder backstops them, exactly as
before this layer existed.

jax-free at module load, like the rest of the serving layer.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class AdmissionRejected(RuntimeError):
    """The query's estimated HBM peak exceeds the whole serving budget —
    it can never be admitted at this budget."""


class AdmissionController:
    """Budgeted admission: ``acquire`` blocks until the claim fits,
    ``release`` frees it.  With ``budget=None`` every acquire is
    immediate (concurrency is still bounded by the scheduler's worker
    pool)."""

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget
        self._cond = threading.Condition()
        self._claims: Dict[int, int] = {}
        self._cache_claims: Dict[str, int] = {}
        self._claimed = 0

    @staticmethod
    def estimate(fingerprint: str) -> int:
        """Estimated peak HBM bytes for ``fingerprint`` from the most
        recent measured history record, or 0 when the plan never ran
        with metrics+history on (cold start admits freely)."""
        if not fingerprint:
            return 0
        from ..obs.history import lookup_latest
        rec = lookup_latest(fingerprint)
        if not rec:
            return 0
        hbm = rec.get("cost", {}).get("hbm", {})
        try:
            return max(int(hbm.get("peak_bytes", 0) or 0), 0)
        except (TypeError, ValueError):
            return 0

    def check(self, estimate: int) -> None:
        """Raise :class:`AdmissionRejected` when ``estimate`` alone can
        never fit the budget.

        With out-of-core spill on (``SRT_SPILL=1``) the premise behind
        rejection — a working set bigger than the budget can never run —
        no longer holds: the spill rung pages cold partitions to
        host/disk, so the query is admitted instead (counted on
        ``serve.admission.spill_admitted``) and the ladder + spill
        manager carry it through."""
        if self.budget is not None and estimate > self.budget:
            from ..resilience.spill import spill_manager
            if spill_manager().enabled:
                from ..obs.metrics import counter
                counter("serve.admission.spill_admitted").inc()
                return
            from ..obs.metrics import counter
            counter("serve.admission.rejected").inc()
            from ..obs import capacity
            capacity.feed_admission_reject(estimate)
            raise AdmissionRejected(
                f"estimated HBM peak of {estimate} bytes exceeds the "
                f"serving budget of {self.budget} bytes "
                f"(SRT_SERVE_HBM_BUDGET)")

    def acquire(self, ticket_id: int, estimate: int) -> bool:
        """Block until ``estimate`` bytes fit under the budget, then
        claim them.  Returns True when the caller had to wait (the
        ticket was HBM-queued, not just pool-queued)."""
        if self.budget is None or estimate <= 0:
            with self._cond:
                self._claims[ticket_id] = max(estimate, 0)
                self._claimed += max(estimate, 0)
            return False
        waited = False
        from ..obs import capacity
        from ..obs.metrics import counter, gauge
        # Proactive spill: if this claim would push us past the
        # watermark, page cold device state out BEFORE queueing on HBM —
        # free memory the claim can use instead of fighting running
        # queries for it.  No-op unless SRT_SPILL is on.
        from ..resilience.spill import maybe_proactive_spill
        maybe_proactive_spill(self.claimed_bytes() + estimate, self.budget)
        with self._cond:
            while self._claimed and self._claimed + estimate > self.budget:
                if not waited:
                    waited = True
                    counter("serve.admission.hbm_waits").inc()
                    capacity.feed_admission_wait()
                self._cond.wait(0.05)
            self._claims[ticket_id] = estimate
            self._claimed += estimate
            gauge("serve.hbm_claimed_bytes").set(self._claimed)
            capacity.feed_hbm(self._claimed)
        return waited

    def release(self, ticket_id: int) -> None:
        with self._cond:
            self._claimed -= self._claims.pop(ticket_id, 0)
            if self._claimed < 0:
                self._claimed = 0
            if self.budget is not None:
                from ..obs.metrics import gauge
                gauge("serve.hbm_claimed_bytes").set(self._claimed)
                from ..obs import capacity
                capacity.feed_hbm(self._claimed)
            self._cond.notify_all()

    def claim_cache(self, key: str, nbytes: int) -> bool:
        """Non-blocking claim for a long-lived cache resident (semantic
        subplan cache, materialized views).  Unlike :meth:`acquire`,
        never waits: a materialization is an optimization, so when the
        claim would not fit under the budget *right now* it is simply
        denied (counted on ``serve.semantic.admission_denied``) and the
        caller skips caching.  Budget-less controllers admit freely."""
        nbytes = max(int(nbytes), 0)
        with self._cond:
            if self.budget is not None and \
                    self._claimed + nbytes > self.budget:
                from ..obs.metrics import counter
                counter("serve.semantic.admission_denied").inc()
                return False
            self._cache_claims[key] = \
                self._cache_claims.get(key, 0) + nbytes
            self._claimed += nbytes
            if self.budget is not None and nbytes:
                from ..obs.metrics import gauge
                gauge("serve.hbm_claimed_bytes").set(self._claimed)
        return True

    def release_cache(self, key: str) -> None:
        """Free a cache resident's claim (eviction or invalidation)."""
        with self._cond:
            self._claimed -= self._cache_claims.pop(key, 0)
            if self._claimed < 0:
                self._claimed = 0
            if self.budget is not None:
                from ..obs.metrics import gauge
                gauge("serve.hbm_claimed_bytes").set(self._claimed)
            self._cond.notify_all()

    def claimed_bytes(self) -> int:
        with self._cond:
            return self._claimed
