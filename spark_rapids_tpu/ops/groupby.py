"""Group-by aggregation, sort-based.

TPU-first redesign of the hash-groupby a GPU engine uses (cuDF's groupby is
part of the reference's capability envelope; BASELINE.json names groupby
throughput as a headline metric): hash tables need scatter-to-random-address,
which the TPU memory system punishes, so groups are formed by the native
multi-key sort (:mod:`.sort`), adjacent-difference boundaries, and
segment reductions over sorted runs.

One host sync materializes the group count; segment reductions run with the
group count bucketed to a power of two so jit caches stay small.

Null semantics follow cuDF/Spark: null keys form their own group (null ==
null for grouping); null *values* are excluded from aggregations; an
all-null group aggregates to null (except counts).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import (DType, FLOAT64, INT64, TypeId, UINT64)
from ..table import Table
from .common import grouping_columns, pow2_bucket

#: Aggregations supported (cuDF basic set).
AGGS = ("count", "count_all", "sum", "min", "max", "mean", "first", "last",
        "var", "std", "nunique", "median")


def _sum_dtype(dtype: DType) -> DType:
    """Accumulation/result type for sums (Spark semantics: widen)."""
    if dtype.is_floating:
        return FLOAT64
    if dtype.type_id in (TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64):
        return UINT64
    if dtype.type_id == TypeId.DECIMAL32 or dtype.type_id == TypeId.DECIMAL64:
        return DType(TypeId.DECIMAL64, dtype.scale)
    return INT64


def _minmax_identity(dtype: DType, for_min: bool):
    np_dt = dtype.np_dtype
    if dtype.is_floating:
        return np_dt.type(np.inf if for_min else -np.inf)
    info = np.iinfo(np_dt)
    return np_dt.type(info.max if for_min else info.min)


class GroupByResult:
    """Carrier so ``groupby(t, keys).agg(...)`` reads naturally."""

    def __init__(self, table: Table, keys: Sequence[str]):
        self._table = table
        self._keys = list(keys)

    def agg(self, aggs: dict[str, Sequence[str] | str]) -> Table:
        spec = []
        for col, hows in aggs.items():
            if isinstance(hows, str):
                hows = [hows]
            for how in hows:
                out_name = col if len(hows) == 1 else f"{col}_{how}"
                spec.append((col, how, out_name))
        return groupby_agg(self._table, self._keys, spec)


def groupby(table: Table, keys: Sequence[str] | str) -> GroupByResult:
    if isinstance(keys, str):
        keys = [keys]
    return GroupByResult(table, keys)


def groupby_agg(table: Table, keys: Sequence[str],
                aggs: Sequence[tuple[str, str, str]]) -> Table:
    """Aggregate ``aggs`` = [(value_col, how, out_name), ...] grouped by ``keys``.

    Output: one row per group, key columns first (group order = sorted key
    order), then aggregate columns.
    """
    for _, how, _ in aggs:
        if how not in AGGS:
            raise ValueError(f"unsupported aggregation {how!r} (have {AGGS})")

    if table.num_rows == 0:
        return _empty_result(table, keys, aggs)

    # Two fused device programs around ONE host sync (the group count):
    # phase 1 sorts keys AND payload columns in a single lax.sort (values
    # ride as extra operands — measured faster than sort-then-gather, and
    # one dispatch instead of one per column), phase 2 computes every
    # aggregate in one program at the pow2-bucketed group count.  Eager
    # per-op dispatch here was the q1 benchmark's dominant cost (~2.2 ms +
    # kernel per op through a tunneled TPU, ~30 ops per groupby).
    key_cols = grouping_columns([table[k] for k in keys])

    # Payload: fixed-width value columns ride the sort.  Strings support
    # first/last (gathered eagerly at the end via the permutation) and
    # count/count_all, which never touch char data — their validity mask
    # rides the sort as a surrogate payload instead.
    pay_names: list[str] = []
    pay_cols: list[Column] = []

    def _ensure_payload(name: str, col: Column):
        if name not in pay_names:
            pay_names.append(name)
            pay_cols.append(col)

    for value_name, how, _ in aggs:
        col = table[value_name]
        if how in ("nunique", "median"):
            if col.dtype.is_two_word:
                raise TypeError(
                    f"aggregation {how!r} on decimal128 column "
                    f"{value_name!r} is not supported; cast to "
                    f"decimal64/float64 first")
            continue                      # dedicated kernels (own sort order)
        if col.offsets is not None or col.dtype.is_two_word:
            # Strings and decimal128 can't ride the 1-D payload sort:
            # first/last gather from the original column at the end,
            # count rides a validity surrogate; arithmetic aggregates
            # need a cast (decimal128 sums exceed any device dtype).
            if how in ("first", "last"):
                continue
            if how in ("count", "count_all"):
                mask = col.valid_mask()
                _ensure_payload(f"__validity__:{value_name}",
                                Column(data=mask.astype(jnp.int8),
                                       validity=col.validity,
                                       dtype=DType(TypeId.INT8)))
                continue
            if how in ("min", "max") and col.offsets is not None:
                # min/max of strings = min/max of dictionary codes (the
                # vocabulary is sorted lexicographically); decoded after
                # aggregation.
                from .strings import dictionary_encode_cached
                codes, _uniq = dictionary_encode_cached(col)
                _ensure_payload(f"__codes__:{value_name}", codes)
                continue
            kind = ("strings" if col.offsets is not None else "decimal128")
            raise TypeError(
                f"aggregation {how!r} is not defined for {kind} "
                f"(column {value_name!r}); cast first")
        _ensure_payload(value_name, col)

    perm, sorted_pay, boundary, count = _groupby_sort(
        tuple(kc.data for kc in key_cols),
        tuple(kc.validity for kc in key_cols),
        tuple(pc.data for pc in pay_cols),
        tuple(pc.validity for pc in pay_cols))
    num_groups = int(count)                       # the one host sync
    seg_count = pow2_bucket(num_groups)

    # Static agg spec for the phase-2 program: (payload index, how,
    # type id, scale) — all hashable ints/strings.
    spec = []
    for value_name, how, _ in aggs:
        col = table[value_name]
        if how in ("nunique", "median"):
            continue
        if col.offsets is not None or col.dtype.is_two_word:
            if how in ("count", "count_all"):
                spec.append((pay_names.index(f"__validity__:{value_name}"),
                             how, int(TypeId.INT8), 0))
            elif how in ("min", "max") and col.offsets is not None:
                spec.append((pay_names.index(f"__codes__:{value_name}"),
                             how, int(TypeId.INT32), 0))
            continue
        spec.append((pay_names.index(value_name), how,
                     int(col.dtype.type_id), col.dtype.scale))
    results = _groupby_aggregate(sorted_pay, boundary, spec=tuple(spec),
                                 seg_count=seg_count)

    starts = jnp.nonzero(boundary, size=num_groups)[0].astype(jnp.int32)
    ends = jnp.concatenate([starts[1:],
                            jnp.array([table.num_rows], jnp.int32)]) - 1

    out: list[tuple[str, Column]] = []
    perm_starts = jnp.take(perm, starts)
    for k in keys:
        out.append((k, table[k].gather(perm_starts)))

    ri = 0
    for value_name, how, out_name in aggs:
        col = table[value_name]
        if how == "nunique":
            vcol = grouping_columns([col])[0]
            counts = _groupby_nunique(
                tuple(kc.data for kc in key_cols),
                tuple(kc.validity for kc in key_cols),
                vcol.data, vcol.validity, seg_count=seg_count)
            out.append((out_name, Column(data=counts[:num_groups],
                                         dtype=INT64)))
            continue
        if how == "median":
            if col.offsets is not None:
                raise TypeError(f"median is not defined for strings "
                                f"(column {value_name!r})")
            med, ok = _groupby_median(
                tuple(kc.data for kc in key_cols),
                tuple(kc.validity for kc in key_cols),
                col.data, col.validity, seg_count=seg_count,
                scale=col.dtype.scale if col.dtype.is_decimal else 0)
            out.append((out_name, Column(data=med[:num_groups],
                                         validity=ok[:num_groups],
                                         dtype=FLOAT64)))
            continue
        if (col.offsets is not None or col.dtype.is_two_word) \
                and how in ("first", "last"):
            idx = starts if how == "first" else ends
            out.append((out_name, col.gather(jnp.take(perm, idx))))
            continue
        if col.offsets is not None and how in ("min", "max"):
            from .strings import dictionary_encode_cached, strings_from_pylist
            _codes, uniq = dictionary_encode_cached(col)
            data, validity = results[ri]
            ri += 1
            if not uniq:
                from ..column import all_null_column
                out.append((out_name, all_null_column(col.dtype, num_groups)))
                continue
            dict_col = strings_from_pylist(list(uniq))
            idx = jnp.clip(data[:num_groups].astype(jnp.int32), 0,
                           len(uniq) - 1)
            s = dict_col.gather(idx)
            if validity is not None:
                v = (validity[:num_groups] if s.validity is None
                     else s.validity & validity[:num_groups])
                s = Column(data=s.data, offsets=s.offsets, validity=v,
                           dtype=s.dtype)
            out.append((out_name, s))
            continue
        data, validity = results[ri]
        ri += 1
        out.append((out_name, Column(
            data=data[:num_groups],
            validity=None if validity is None else validity[:num_groups],
            dtype=_agg_out_dtype(col.dtype, how))))
    return Table(out)


@jax.jit
def _groupby_sort(key_datas, key_valids, pay_datas, pay_valids):
    """One ``lax.sort`` over null-rank/value key pairs + iota + payloads.

    Null rows' value operands are masked to zero so equality among nulls is
    positional-payload-independent (null == null grouping); stability makes
    the masked order deterministic.  Returns (permutation, sorted payload
    (data, validity) pairs, group boundary, group count).
    """
    from .common import adjacent_differs, grouping_sort_operands
    n = key_datas[0].shape[0]
    ops = grouping_sort_operands(key_datas, key_valids)
    iota = jnp.arange(n, dtype=jnp.int32)
    flat_pay: list[jax.Array] = []
    for d, v in zip(pay_datas, pay_valids):
        flat_pay.append(d)
        if v is not None:
            flat_pay.append(v)
    sorted_all = jax.lax.sort(ops + [iota] + flat_pay, dimension=0,
                              is_stable=True, num_keys=len(ops))
    sorted_ops = sorted_all[:len(ops)]
    perm = sorted_all[len(ops)]
    rest = sorted_all[len(ops) + 1:]
    sorted_pay = []
    i = 0
    for d, v in zip(pay_datas, pay_valids):
        sd = rest[i]
        i += 1
        sv = None
        if v is not None:
            sv = rest[i]
            i += 1
        sorted_pay.append((sd, sv))
    boundary = jnp.zeros(n, jnp.bool_)
    for k in range(len(key_datas)):
        boundary = boundary | adjacent_differs(sorted_ops[2 * k])
        boundary = boundary | adjacent_differs(sorted_ops[2 * k + 1])
    count = jnp.sum(boundary.astype(jnp.int32))
    return perm, tuple(sorted_pay), boundary, count


@functools.partial(jax.jit, static_argnames=("seg_count", "scale"))
def _groupby_median(key_datas, key_valids, value_data, value_valid, *,
                    seg_count, scale):
    """Per-group median with linear interpolation (cuDF groupby median):
    sort by (keys..., value), locate each group's valid run, average the
    two middle elements.  Null values are excluded; all-null groups are
    null.  Returns (float64 medians, validity)."""
    from .common import (adjacent_differs, chunked_cumsum,
                         grouping_sort_operands)
    n = value_data.shape[0]
    key_ops = grouping_sort_operands(key_datas, key_valids)
    val_ops = grouping_sort_operands((value_data,), (value_valid,))
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_all = jax.lax.sort(key_ops + val_ops + [iota], dimension=0,
                              is_stable=False,
                              num_keys=len(key_ops) + len(val_ops))
    perm = sorted_all[-1]
    key_boundary = jnp.zeros(n, jnp.bool_)
    for op in sorted_all[:len(key_ops)]:
        key_boundary = key_boundary | adjacent_differs(op)
    valid_sorted = sorted_all[len(key_ops)] == 1   # value null-rank
    group_id = chunked_cumsum(key_boundary.astype(jnp.int32)) - 1

    starts = jnp.nonzero(key_boundary, size=seg_count,
                         fill_value=n)[0].astype(jnp.int32)
    nulls = jax.ops.segment_sum((~valid_sorted).astype(jnp.int32), group_id,
                                num_segments=seg_count,
                                indices_are_sorted=True)
    vcount = jax.ops.segment_sum(valid_sorted.astype(jnp.int32), group_id,
                                 num_segments=seg_count,
                                 indices_are_sorted=True)
    # valid run of group g: [starts + nulls, starts + nulls + vcount)
    # (value grouping operands rank nulls first within the key group)
    run0 = starts + nulls
    lo = run0 + jnp.maximum(vcount - 1, 0) // 2
    hi = run0 + vcount // 2
    sorted_vals = jnp.take(value_data, jnp.take(
        perm, jnp.clip(jnp.stack([lo, hi]), 0, max(n - 1, 0))))
    med = (sorted_vals[0].astype(jnp.float64)
           + sorted_vals[1].astype(jnp.float64)) / 2.0
    if scale:
        med = med * (10.0 ** scale)
    return med, vcount > 0


@functools.partial(jax.jit, static_argnames=("seg_count",))
def _groupby_nunique(key_datas, key_valids, value_data, value_valid, *,
                     seg_count):
    """Distinct non-null values per group (cuDF ``nunique``, nulls
    excluded).

    Own sort order — (keys..., value) — so it cannot ride the shared
    groupby sort: a distinct-run head is a VALID row whose (key, value)
    pair differs from the previous row; per-group counts are segment sums
    of head flags.  Group order matches the main groupby kernel (sorted
    keys), so results align positionally."""
    from .common import distinct_run_heads, grouping_sort_operands
    key_ops = grouping_sort_operands(key_datas, key_valids)
    val_ops = grouping_sort_operands((value_data,), (value_valid,))
    sorted_all = jax.lax.sort(key_ops + val_ops, dimension=0, is_stable=False,
                              num_keys=len(key_ops) + len(val_ops))
    key_boundary, head = distinct_run_heads(
        sorted_all[:len(key_ops)], sorted_all[len(key_ops):])

    group_id = jnp.cumsum(key_boundary.astype(jnp.int32)) - 1
    return jax.ops.segment_sum(head.astype(jnp.int64), group_id,
                               num_segments=seg_count,
                               indices_are_sorted=True)


@functools.partial(jax.jit, static_argnames=("spec", "seg_count"))
def _groupby_aggregate(sorted_pay, boundary, *, spec, seg_count):
    """All aggregates in one program at the bucketed group count.

    ``spec``: tuple of (payload index, how, type id, scale).  Returns a
    list of (data, validity-or-None) pairs at length ``seg_count`` (the
    caller slices to the real group count and attaches output dtypes via
    :func:`_agg_out_dtype`).
    """
    n = boundary.shape[0]
    group_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    starts = jnp.nonzero(boundary, size=seg_count,
                         fill_value=n)[0].astype(jnp.int32)
    ends = jnp.concatenate([starts[1:], jnp.array([n], jnp.int32)]) - 1
    outputs = []
    for pay_idx, how, type_id, scale in spec:
        d, v = sorted_pay[pay_idx]
        dtype = DType(TypeId(type_id), scale)
        outputs.append(_segment_agg(d, v, dtype, group_id, starts, ends,
                                    seg_count, how))
    return outputs


def _agg_out_dtype(dtype: DType, how: str) -> DType:
    """Result dtype per aggregation (host-side; mirrors _segment_agg)."""
    if how in ("count", "count_all", "nunique"):
        return INT64
    if how == "sum":
        return _sum_dtype(dtype)
    if how in ("mean", "var", "std", "median"):
        return FLOAT64
    return dtype                    # min/max/first/last keep the input type


def _empty_result(table: Table, keys: Sequence[str],
                  aggs: Sequence[tuple[str, str, str]]) -> Table:
    out: list[tuple[str, Column]] = []
    for k in keys:
        out.append((k, table[k]))
    for value_name, how, out_name in aggs:
        src = table[value_name]
        if how in ("count", "count_all", "nunique"):
            dtype = INT64
        elif how == "sum":
            dtype = _sum_dtype(src.dtype)
        elif how in ("mean", "var", "std", "median"):
            dtype = FLOAT64
        else:
            dtype = src.dtype
        out.append((out_name, Column(data=jnp.zeros(0, dtype.jnp_dtype),
                                     dtype=dtype)))
    return Table(out)


def _segment_agg(data: jax.Array, validity, dtype: DType,
                 group_id: jax.Array, starts: jax.Array, ends: jax.Array,
                 seg_count: int, how: str):
    """One aggregation over sorted segments → (values, validity-or-None).

    Traced inside :func:`_groupby_aggregate`; all segment reductions use
    ``indices_are_sorted`` (group ids ARE sorted) and the bucketed segment
    count so one compiled program serves many group cardinalities.
    """
    n = data.shape[0]
    valid = jnp.ones(n, jnp.bool_) if validity is None else validity
    counts = jax.ops.segment_sum(valid.astype(jnp.int64), group_id,
                                 num_segments=seg_count,
                                 indices_are_sorted=True)
    if how == "count":
        return counts, None
    if how == "count_all":
        return jax.ops.segment_sum(jnp.ones(n, jnp.int64), group_id,
                                   num_segments=seg_count,
                                   indices_are_sorted=True), None
    if how in ("first", "last"):
        idx = starts if how == "first" else ends
        vals = jnp.take(data, idx)
        out_valid = jnp.take(valid, idx) if validity is not None else None
        return vals, out_valid

    has_valid = counts > 0

    if how in ("sum", "mean", "var", "std"):
        acc_dtype = _sum_dtype(dtype)
        vals = jnp.where(valid, data,
                         jnp.zeros((), data.dtype)).astype(acc_dtype.jnp_dtype)
        sums = jax.ops.segment_sum(vals, group_id, num_segments=seg_count,
                                   indices_are_sorted=True)
        if how == "sum":
            return sums, has_valid
        # mean/var/std return logical FLOAT64 values: decimals apply 10**scale.
        scale_factor = 10.0 ** dtype.scale if dtype.is_decimal else 1.0
        fsums = sums.astype(jnp.float64) * scale_factor
        fcounts = counts.astype(jnp.float64)
        if how == "mean":
            return fsums / jnp.maximum(fcounts, 1.0), has_valid
        # var/std (ddof=1, Spark sample variance)
        sq = jnp.where(valid, data.astype(jnp.float64) * scale_factor, 0.0) ** 2
        sumsq = jax.ops.segment_sum(sq, group_id, num_segments=seg_count,
                                    indices_are_sorted=True)
        denom = jnp.maximum(fcounts - 1.0, 1.0)
        var = (sumsq - fsums * fsums / jnp.maximum(fcounts, 1.0)) / denom
        var = jnp.maximum(var, 0.0)             # clamp fp round-off
        ok = counts > 1
        if how == "var":
            return var, ok
        return jnp.sqrt(var), ok

    # min / max
    for_min = how == "min"
    ident = _minmax_identity(dtype, for_min)
    vals = jnp.where(valid, data, ident)
    seg = jax.ops.segment_min if for_min else jax.ops.segment_max
    res = seg(vals, group_id, num_segments=seg_count,
              indices_are_sorted=True)
    return res, has_valid
