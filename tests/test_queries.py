"""End-to-end query-shaped integration tests (BASELINE.json configs #2/#3).

TPC-H q1 (scan -> filter -> projected arithmetic -> group-by agg -> sort)
and a TPC-DS-style fact-dimension join + aggregation, run through the real
framework pipeline — Parquet scan included — and verified against an
independent numpy oracle.  The distributed variants run the same queries
over the 8-virtual-device mesh (dist shuffle + groupby/join).
"""

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu import ops
from spark_rapids_tpu.io.parquet import read_parquet, write_parquet
from spark_rapids_tpu.ops.binary import binary_op

#: compile-heavy module: full tier only (smoke = -m 'not full').
pytestmark = pytest.mark.full


N = 20_000
CUTOFF_DAYS = 10_500     # the l_shipdate <= date '1998-09-02' analog


def make_lineitem(rng, n=N):
    """A lineitem-shaped table: flag/status codes, qty, price, disc, tax."""
    return {
        "l_returnflag": rng.integers(0, 3, n).astype(np.int8),    # A/N/R codes
        "l_linestatus": rng.integers(0, 2, n).astype(np.int8),    # F/O codes
        "l_quantity": rng.integers(1, 51, n).astype(np.int64),
        "l_extendedprice": np.round(rng.uniform(900, 105000, n), 2),
        "l_discount": np.round(rng.uniform(0.0, 0.1, n), 2),
        "l_tax": np.round(rng.uniform(0.0, 0.08, n), 2),
        "l_shipdate": rng.integers(8000, 11000, n).astype(np.int32),  # days
    }


def q1_oracle(cols):
    """Independent numpy implementation of the q1 aggregation."""
    sel = cols["l_shipdate"] <= CUTOFF_DAYS
    flag = cols["l_returnflag"][sel]
    status = cols["l_linestatus"][sel]
    qty = cols["l_quantity"][sel].astype(np.float64)
    price = cols["l_extendedprice"][sel]
    disc = cols["l_discount"][sel]
    tax = cols["l_tax"][sel]
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    out = {}
    for f in np.unique(flag):
        for s in np.unique(status[flag == f]):
            g = (flag == f) & (status == s)
            out[(int(f), int(s))] = dict(
                sum_qty=qty[g].sum(), sum_base_price=price[g].sum(),
                sum_disc_price=disc_price[g].sum(), sum_charge=charge[g].sum(),
                avg_qty=qty[g].mean(), avg_price=price[g].mean(),
                avg_disc=disc[g].mean(), count_order=int(g.sum()))
    return out


def run_q1(table):
    """TPC-H q1 through the framework ops (what the Spark plan would emit)."""
    pred = binary_op(table["l_shipdate"], CUTOFF_DAYS, "le")
    t = ops.apply_boolean_mask(table, pred)
    one_minus_disc = binary_op(1.0, t["l_discount"], "sub")
    disc_price = binary_op(t["l_extendedprice"], one_minus_disc, "mul")
    charge = binary_op(disc_price, binary_op(1.0, t["l_tax"], "add"), "mul")
    t = t.with_column("disc_price", disc_price).with_column("charge", charge)
    agg = ops.groupby_agg(
        t, ["l_returnflag", "l_linestatus"],
        [("l_quantity", "sum", "sum_qty"),
         ("l_extendedprice", "sum", "sum_base_price"),
         ("disc_price", "sum", "sum_disc_price"),
         ("charge", "sum", "sum_charge"),
         ("l_quantity", "mean", "avg_qty"),
         ("l_extendedprice", "mean", "avg_price"),
         ("l_discount", "mean", "avg_disc"),
         ("l_quantity", "count", "count_order")])
    return ops.sort_by(agg, ["l_returnflag", "l_linestatus"])


def assert_q1_matches(result, oracle):
    got = result.to_pydict()
    keys = list(zip(got["l_returnflag"], got["l_linestatus"]))
    assert keys == sorted(oracle)                  # sorted group order
    for i, k in enumerate(keys):
        exp = oracle[k]
        assert got["count_order"][i] == exp["count_order"]
        for field in ("sum_qty", "sum_base_price", "sum_disc_price",
                      "sum_charge", "avg_qty", "avg_price", "avg_disc"):
            np.testing.assert_allclose(got[field][i], exp[field], rtol=1e-9)


def test_tpch_q1_via_parquet(tmp_path, rng):
    cols = make_lineitem(rng)
    table = srt.Table.from_pydict({k: v.tolist() for k, v in cols.items()},
                                  dtypes={
        "l_returnflag": dt.INT8, "l_linestatus": dt.INT8,
        "l_quantity": dt.INT64, "l_extendedprice": dt.FLOAT64,
        "l_discount": dt.FLOAT64, "l_tax": dt.FLOAT64,
        "l_shipdate": dt.TIMESTAMP_DAYS})
    path = tmp_path / "lineitem.parquet"
    write_parquet(table, path)
    scanned = read_parquet(path)                   # full pipeline incl. scan
    assert_q1_matches(run_q1(scanned), q1_oracle(cols))


def test_tpch_q1_column_pruning(tmp_path, rng):
    cols = make_lineitem(rng, 2000)
    table = srt.Table.from_pydict({k: v.tolist() for k, v in cols.items()},
                                  dtypes={
        "l_returnflag": dt.INT8, "l_linestatus": dt.INT8,
        "l_quantity": dt.INT64, "l_extendedprice": dt.FLOAT64,
        "l_discount": dt.FLOAT64, "l_tax": dt.FLOAT64,
        "l_shipdate": dt.TIMESTAMP_DAYS})
    path = tmp_path / "lineitem.parquet"
    write_parquet(table, path)
    pruned = read_parquet(path, columns=["l_returnflag", "l_quantity"])
    assert list(pruned.names) == ["l_returnflag", "l_quantity"]
    assert pruned.num_rows == 2000


def test_fact_dim_join_agg(rng):
    """TPC-DS-style: fact join dim on key, then grouped revenue by category."""
    n, n_dim = 30_000, 500
    fact_key = rng.integers(0, n_dim, n).astype(np.int64)
    revenue = np.round(rng.uniform(1, 1000, n), 2)
    category = rng.integers(0, 8, n_dim).astype(np.int32)

    fact = srt.Table.from_pydict(
        {"item_key": fact_key.tolist(), "revenue": revenue.tolist()},
        dtypes={"item_key": dt.INT64, "revenue": dt.FLOAT64})
    dim = srt.Table.from_pydict(
        {"item_key": list(range(n_dim)), "category": category.tolist()},
        dtypes={"item_key": dt.INT64, "category": dt.INT32})

    joined = ops.join(fact, dim, on=["item_key"], how="inner")
    agg = ops.groupby_agg(joined, ["category"],
                          [("revenue", "sum", "revenue_sum"),
                           ("revenue", "count", "n")])
    result = ops.sort_by(agg, ["category"]).to_pydict()

    expect = {}
    for c in range(8):
        sel = category[fact_key] == c
        expect[c] = (revenue[sel].sum(), int(sel.sum()))
    assert result["category"] == [c for c in sorted(expect) if expect[c][1]]
    for i, c in enumerate(result["category"]):
        np.testing.assert_allclose(result["revenue_sum"][i], expect[c][0],
                                   rtol=1e-9)
        assert result["n"][i] == expect[c][1]


@pytest.mark.parametrize("n_devices", [8])
def test_tpch_q1_distributed(n_devices, rng):
    """The q1 aggregation over the mesh: shuffle + distributed groupby."""
    import jax

    from spark_rapids_tpu.parallel import (collect, dist_groupby, make_mesh,
                                           shard_table)

    cols = make_lineitem(rng, 4096)
    sel = cols["l_shipdate"] <= CUTOFF_DAYS
    filtered = {k: v[sel] for k, v in cols.items()}
    oracle = q1_oracle(cols)

    mesh = make_mesh(jax.devices()[:n_devices])
    one_minus_disc = 1.0 - filtered["l_discount"]
    disc_price = filtered["l_extendedprice"] * one_minus_disc
    table = srt.Table.from_pydict({
        "flag": filtered["l_returnflag"].tolist(),
        "status": filtered["l_linestatus"].tolist(),
        "qty": filtered["l_quantity"].tolist(),
        "price": filtered["l_extendedprice"].tolist(),
        "disc_price": disc_price.tolist(),
    }, dtypes={"flag": dt.INT8, "status": dt.INT8, "qty": dt.INT64,
               "price": dt.FLOAT64, "disc_price": dt.FLOAT64})
    dtab = shard_table(table, mesh)
    out = dist_groupby(dtab, mesh, ["flag", "status"],
                       [("qty", "sum", "sum_qty"),
                        ("price", "sum", "sum_base_price"),
                        ("disc_price", "sum", "sum_disc_price"),
                        ("qty", "count", "count_order")])
    got = ops.sort_by(collect(out), ["flag", "status"]).to_pydict()
    keys = list(zip(got["flag"], got["status"]))
    assert keys == sorted(oracle)
    for i, k in enumerate(keys):
        np.testing.assert_allclose(got["sum_qty"][i], oracle[k]["sum_qty"])
        np.testing.assert_allclose(got["sum_disc_price"][i],
                                   oracle[k]["sum_disc_price"], rtol=1e-9)
        assert got["count_order"][i] == oracle[k]["count_order"]
