"""Distributed groupby and join: shuffle + static-shape local kernels.

Both ops follow the same TPU-native recipe (SURVEY.md §7): hash-shuffle rows
by key so equal keys colocate, then run a *fixed-shape* local kernel per
shard under ``shard_map`` — sorted segments for groupby, searchsorted merge
for join — producing padded outputs with row masks.  Zero host syncs inside
the compiled program; the only dynamic decisions (shuffle overflow, join
output capacity) surface as flags the caller reacts to.

This is the engine's answer to the reference system's executor-side
hash aggregation / shuffled hash join over UCX (spark-rapids plugin world):
same query semantics, but every step is a sort/scan/gather XLA already knows
how to tile onto the TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from ..column import Column
from ..dtypes import FLOAT64, INT64
from ..ops.common import adjacent_differs, null_safe_equal_at
from ..table import Table
from .mesh import DistTable, _DIST_PROGRAMS, mesh_cache_key, shard_map
from .shuffle import shuffle


def _dist_program(key: tuple, build):
    """Cached-compile lookup for the local shard_map kernels below:
    bounded LRU shared with the shuffle program cache (mesh.
    _DIST_PROGRAMS, ``SRT_COMPILE_CACHE_CAP``), cleared wholesale by the
    recovery ladder's eviction rung.  The bodies close over arity/how/
    capacity only — jit re-specializes per dtype — so one entry serves
    every same-shape op on the mesh instead of retracing per call."""
    from ..exec.compile import _lru_lookup
    return _lru_lookup(_DIST_PROGRAMS, key, build, "dist.programs")[0]

_DIST_AGGS = ("sum", "count", "min", "max", "mean")


def dist_groupby(dist: DistTable, mesh: Mesh, keys: Sequence[str],
                 aggs: Sequence[tuple[str, str, str]],
                 bucket_size: Optional[int] = None) -> DistTable:
    """Distributed group-by: one shuffle, then per-shard sorted segments.

    ``aggs`` = [(value_col, how, out_name)] with how in {sum, count, min,
    max, mean}.  Output: a DistTable of group rows (padded; ``row_mask``
    marks real groups).
    """
    for _, how, _ in aggs:
        if how not in _DIST_AGGS:
            raise ValueError(f"unsupported distributed agg {how!r}")
    shuffled = shuffle(dist, mesh, keys, bucket_size=bucket_size)
    return _local_groupby(shuffled, mesh, list(keys), list(aggs))


def _local_groupby(dist: DistTable, mesh: Mesh, keys: list[str],
                   aggs: list[tuple[str, str, str]]) -> DistTable:
    axis = mesh.axis_names[0]
    table = dist.table
    key_cols = [table[k] for k in keys]
    val_cols = [table[v] for v, _, _ in aggs]
    hows = tuple(how for _, how, _ in aggs)

    body = _dist_program(
        ("groupby", mesh_cache_key(mesh), len(key_cols), hows),
        lambda: _build_groupby_body(mesh, axis, len(key_cols), hows))

    flat_in = [dist.row_mask]
    for kc in key_cols:
        flat_in += [kc.data]
    for kc in key_cols:
        flat_in += [kc.valid_mask()]
    for vc in val_cols:
        flat_in += [vc.data]
    for vc in val_cols:
        flat_in += [vc.valid_mask()]

    results = body(*flat_in)
    new_mask = results[0]
    pos = 1
    cols = []
    for k, kc in zip(keys, key_cols):
        data, valid = results[pos], results[pos + 1]
        pos += 2
        validity = None if kc.validity is None else valid
        cols.append((k, Column(data=data, validity=validity, dtype=kc.dtype)))
    for (vname, how, out_name), vc in zip(aggs, val_cols):
        data, valid = results[pos], results[pos + 1]
        pos += 2
        if how == "count":
            dtype = INT64
        elif how == "mean":
            dtype = FLOAT64
        elif how == "sum":
            from ..ops.groupby import _sum_dtype
            dtype = _sum_dtype(vc.dtype)
        else:
            dtype = vc.dtype
        cols.append((out_name, Column(data=data.astype(dtype.jnp_dtype),
                                      validity=valid, dtype=dtype)))
    return DistTable(table=Table(cols), row_mask=new_mask)


def _build_groupby_body(mesh: Mesh, axis: str, nk: int, hows: tuple):
    nv = len(hows)
    n_in = 1 + 2 * nk + 2 * nv

    @partial(shard_map, mesh=mesh,
             in_specs=(PartitionSpec(axis),) * n_in,
             out_specs=(PartitionSpec(axis),) * (1 + 2 * nk + 2 * nv))
    def body(mask, *flat):
        kdatas = flat[:nk]
        kvalids = flat[nk:2 * nk]
        vdatas = flat[2 * nk:2 * nk + nv]
        vvalids = flat[2 * nk + nv:]
        C = mask.shape[0]

        # Sort local rows by (dead-last, keys...) — dead slots group at the end.
        operands = [(~mask).astype(jnp.uint8)]
        for kd, kv in zip(kdatas, kvalids):
            operands.append(jnp.where(kv, jnp.uint8(1), jnp.uint8(0)))
            val = kd
            if jnp.issubdtype(val.dtype, jnp.floating):
                val = jnp.where(val != val, jnp.array(jnp.nan, val.dtype), val)
            operands.append(val)
        iota = jnp.arange(C, dtype=jnp.int32)
        sorted_ops = jax.lax.sort(operands + [iota], dimension=0,
                                  is_stable=True, num_keys=len(operands))
        perm = sorted_ops[-1]
        smask = jnp.take(mask, perm)
        skd = [jnp.take(kd, perm) for kd in kdatas]
        skv = [jnp.take(kv, perm) for kv in kvalids]

        # Boundaries (first row of each group); dead rows are never starts.
        # Grouping equality is defined once, in ops.common.adjacent_differs
        # (null == null, NaN == NaN) — shared with the local engine so
        # distributed results can never drift from the local oracle.
        boundary = jnp.zeros(C, jnp.bool_)
        for kd, kv in zip(skd, skv):
            boundary = boundary | adjacent_differs(kd, kv)
        boundary = boundary | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), smask[1:] != smask[:-1]])
        boundary = boundary & smask
        gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        gid = jnp.where(smask, gid, C - 1)     # dead rows -> scratch segment

        outs = [boundary]                       # new row mask = group starts
        for kd, kv in zip(skd, skv):
            outs.append(kd)                     # group key at start position
            outs.append(kv)

        for how, vd, vv in zip(hows, vdatas, vvalids):
            svd = jnp.take(vd, perm)
            svv = jnp.take(vv, perm) & smask
            counts = jax.ops.segment_sum(svv.astype(jnp.int64), gid,
                                         num_segments=C)
            counts_at = jnp.take(counts, gid)
            if how == "count":
                outs.append(counts_at)
                outs.append(jnp.ones(C, jnp.bool_))
                continue
            if how in ("sum", "mean"):
                acc_dt = jnp.float64 if how == "mean" or \
                    jnp.issubdtype(svd.dtype, jnp.floating) else jnp.int64
                vals = jnp.where(svv, svd, svd.dtype.type(0)).astype(acc_dt)
                sums = jax.ops.segment_sum(vals, gid, num_segments=C)
                if how == "mean":
                    res = jnp.take(sums, gid) / jnp.maximum(
                        counts_at.astype(jnp.float64), 1.0)
                else:
                    res = jnp.take(sums, gid)
                outs.append(res)
                outs.append(counts_at > 0)
                continue
            # min / max
            if jnp.issubdtype(svd.dtype, jnp.floating):
                ident = jnp.array(np.inf if how == "min" else -np.inf, svd.dtype)
            else:
                info = np.iinfo(np.dtype(svd.dtype))
                ident = jnp.array(info.max if how == "min" else info.min,
                                  svd.dtype)
            vals = jnp.where(svv, svd, ident)
            seg = jax.ops.segment_min if how == "min" else jax.ops.segment_max
            res = jnp.take(seg(vals, gid, num_segments=C), gid)
            outs.append(res)
            outs.append(counts_at > 0)
        return tuple(outs)

    return jax.jit(body)


def dist_join(left: DistTable, right: DistTable, mesh: Mesh,
              on: Sequence[str], how: str = "inner",
              out_capacity_per_shard: Optional[int] = None,
              bucket_size: Optional[int] = None) -> DistTable:
    """Distributed equi-join: co-shuffle both sides, merge-join per shard.

    Join keys must share names (``on``).  Output is padded to
    ``out_capacity_per_shard`` rows per shard (default: left shard capacity
    x2).  If any shard's join expansion exceeds that capacity, the op
    detects it (one host-synced scalar) and automatically re-runs the local
    kernel with the required capacity — callers never see an overflow, but
    a badly under-sized ``out_capacity_per_shard`` costs a second jitted
    pass.
    """
    if how not in ("inner", "left"):
        raise ValueError(f"unsupported distributed join type {how!r}")
    from ..resilience import dist_guard, fault_point
    lsh = shuffle(left, mesh, on, bucket_size=bucket_size)
    rsh = shuffle(right, mesh, on, bucket_size=bucket_size)
    P = mesh.devices.size
    Cl = lsh.capacity_total // P
    if out_capacity_per_shard is None:
        out_capacity_per_shard = 2 * Cl

    def run_local(cap):
        # Named fault site: the merge-join's pmax of the needed output
        # capacity is this op's mesh collective, and the int() below
        # blocks on the whole exchange — a shard-targeted "collective"
        # SRT_FAULT spec fails here, and the stall watchdog around this
        # closure turns a wedged mesh into DistStallError.
        for s in range(P):
            fault_point("collective", shard=s)
        import time as _time
        from ..utils.memory import record_host_sync
        from .mesh import record_ici
        t0 = _time.perf_counter()
        out, needed = _local_join(lsh, rsh, mesh, list(on), how, cap)
        needed = int(needed)         # blocks on the whole joined exchange
        dur_s = _time.perf_counter() - t0
        record_host_sync("dist.join.needed", 8, seconds=dur_s)
        # The capacity pmax is this op's own collective (the shuffles
        # above account their all_to_alls separately): a P-scalar
        # all-reduce, so bytes are ~8*P and record_ici's floor keeps it
        # visible in ``ici.us``.
        record_ici(8 * P)
        return out, needed

    out, max_needed = dist_guard(
        "dist.join", lambda: run_local(out_capacity_per_shard))
    if max_needed > out_capacity_per_shard:
        out, _ = dist_guard("dist.join", lambda: run_local(max_needed))
    return out


def _local_join(lsh: DistTable, rsh: DistTable, mesh: Mesh, on: list[str],
                how: str, Cout: int):
    axis = mesh.axis_names[0]
    lkeys = [lsh.table[k] for k in on]
    rkeys = [rsh.table[k] for k in on]
    for lk, rk in zip(lkeys, rkeys):
        if lk.dtype != rk.dtype:
            raise ValueError("join key dtype mismatch (cast first)")
    # Output naming mirrors ops.join: shared key columns come from the left
    # side, overlapping non-key names get ('_x', '_y') suffixes.
    lothers = []
    overlap = (set(lsh.table.names) & set(rsh.table.names)) - set(on)
    for n, c in lsh.table.items():
        lothers.append((n + "_x" if n in overlap else n, c))
    rothers = [(n + "_y" if n in overlap else n, c)
               for n, c in rsh.table.items() if n not in on]

    def flatten_side(cols):
        flat = []
        for c in cols:
            flat += [c.data, c.valid_mask()]
        return flat

    l_flat = flatten_side([c for _, c in lothers])
    r_flat = flatten_side([c for _, c in rothers])
    lk_flat = flatten_side(lkeys)
    rk_flat = flatten_side(rkeys)

    body = _dist_program(
        ("join", mesh_cache_key(mesh), len(on), len(lothers), len(rothers),
         how, Cout),
        lambda: _build_join_body(mesh, axis, len(on), len(lothers),
                                 len(rothers), how, Cout))

    flat_in = [lsh.row_mask, rsh.row_mask] + lk_flat + rk_flat + l_flat + r_flat
    results = body(*flat_in)
    new_mask = results[0]
    needed = results[-1]
    pos = 1
    cols = []
    for (name, c) in lothers:
        data, valid = results[pos], results[pos + 1]
        pos += 2
        cols.append((name, Column(data=data, validity=valid, dtype=c.dtype)))
    for (name, c) in rothers:
        data, valid = results[pos], results[pos + 1]
        pos += 2
        cols.append((name, Column(data=data, validity=valid, dtype=c.dtype)))
    return DistTable(table=Table(cols), row_mask=new_mask), needed


def _build_join_body(mesh: Mesh, axis: str, nk: int, nlo: int, nro: int,
                     how: str, Cout: int):
    n_in = 2 + 2 * (nk + nk + nlo + nro)
    n_out = 1 + 2 * (nlo + nro) + 1

    @partial(shard_map, mesh=mesh,
             in_specs=(PartitionSpec(axis),) * n_in,
             out_specs=((PartitionSpec(axis),) * (n_out - 1)
                        + (PartitionSpec(),)))
    def body(lmask, rmask, *flat):
        i = 0
        def take_pairs(count):
            nonlocal i
            out = [(flat[i + 2 * j], flat[i + 2 * j + 1]) for j in range(count)]
            i += 2 * count
            return out
        lk = take_pairs(nk)
        rk = take_pairs(nk)
        lo_cols = take_pairs(nlo)
        ro_cols = take_pairs(nro)
        Cl = lmask.shape[0]
        Cr = rmask.shape[0]

        # Surrogate single key: hash of key tuple (the SAME hash_arrays that
        # routed the shuffle, so colocation and matching stay equality-
        # compatible by construction).  The hash probe is a candidate filter
        # only: every emitted pair is re-verified against the real key
        # columns below (null_safe_equal_at), as cuDF/spark-rapids hash
        # joins verify equality after the probe.  Null keys never match.
        def key_hash(pairs):
            from .hashing import hash_arrays
            h = hash_arrays([(kd, kv) for kd, kv in pairs], seed=17)
            any_null = jnp.zeros(h.shape[0], jnp.bool_)
            for _, kv in pairs:
                any_null = any_null | ~kv
            return h, any_null

        lh, lnull = key_hash(lk)
        rh, rnull = key_hash(rk)
        # Dead/null-key rows get side-distinct sentinels that never match.
        lh = jnp.where(lmask & ~lnull, lh, jnp.uint64(0xDEAD00000000DEAD))
        rh = jnp.where(rmask & ~rnull, rh, jnp.uint64(0xBEEF00000000BEEF))

        rorder = jnp.argsort(rh, stable=True)
        rh_sorted = jnp.take(rh, rorder)
        lo = jnp.searchsorted(rh_sorted, lh, side="left")
        hi = jnp.searchsorted(rh_sorted, lh, side="right")
        counts = jnp.where(lmask & ~lnull, hi - lo, 0).astype(jnp.int32)
        if how == "left":
            counts_out = jnp.where(lmask, jnp.maximum(counts, 1), 0)
        else:
            counts_out = counts
        # Expansion bookkeeping in int64: per-shard output positions can
        # exceed 2**31 under heavy key skew; int32 cumsum would wrap and
        # silently truncate the join instead of triggering the capacity
        # retry.  The per-slot index math, though, runs at int32 whenever
        # the output fits (every realistic shard) — TPU emulates int64, so
        # the hot gather-index path shouldn't pay x64 cost just for
        # overflow detection.
        bounds64 = jnp.cumsum(counts_out.astype(jnp.int64))
        total = bounds64[-1] if Cl else jnp.int64(0)
        idx_dt = jnp.int32 if Cout < 2**31 else jnp.int64
        bounds = jnp.clip(bounds64, 0, 2**31 - 1).astype(idx_dt) \
            if idx_dt == jnp.int32 else bounds64
        starts = bounds - counts_out.astype(idx_dt)

        pos = jnp.arange(Cout, dtype=idx_dt)
        lrow = jnp.searchsorted(bounds, pos, side="right").astype(jnp.int32)
        lrow_c = jnp.clip(lrow, 0, Cl - 1)
        k = (pos - jnp.take(starts, lrow_c)).astype(jnp.int32)
        matched = jnp.take(counts, lrow_c) > 0
        rpos = jnp.take(lo, lrow_c).astype(jnp.int32) + k
        rrow = jnp.take(rorder, jnp.clip(rpos, 0, Cr - 1))
        out_mask = pos.astype(jnp.int64) < total

        # Post-probe verification: the probe matched on the 64-bit hash; a
        # collision between distinct key tuples (or a left hash landing on
        # the dead-right sentinel) must not emit a bogus pair.  Verify the
        # real key columns and that the right row is live with a non-null
        # key.  A collided pair becomes a dead output slot (for "left", the
        # affected left row is dropped rather than null-padded — the
        # ~2^-64-probability residual of the hash probe).
        verified = jnp.take(rmask & ~rnull, rrow)
        for (ld, lv), (rd, rv) in zip(lk, rk):
            verified = verified & null_safe_equal_at(
                jnp.take(ld, lrow_c, axis=0), jnp.take(lv, lrow_c),
                jnp.take(rd, rrow, axis=0), jnp.take(rv, rrow))
        right_live = matched & verified
        if how == "left":
            out_mask = out_mask & (verified | ~matched)
        else:
            out_mask = out_mask & verified

        outs = [out_mask]
        for ld, lv in lo_cols:
            outs.append(jnp.take(ld, lrow_c, axis=0))
            outs.append(jnp.take(lv, lrow_c) & out_mask)
        for rd, rv in ro_cols:
            outs.append(jnp.take(rd, rrow, axis=0))
            outs.append(jnp.take(rv, rrow) & right_live & out_mask)
        needed = jax.lax.pmax(total, axis)
        return tuple(outs) + (needed,)

    return jax.jit(body)
