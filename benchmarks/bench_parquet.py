"""Parquet scan benchmark: native device decoder vs Arrow host reader.

Measures end-to-end file→device-Table throughput for both engines on the
same 4M-row mixed fixed-width + dictionary-string file (snappy), two
configurations:

* **quiet host** — engines interleaved A/B per rep, median of 5 (the
  tunnel's transfer bandwidth swings run-to-run; medians of interleaved
  samples compare engines under the same conditions);
* **contended host** — the same interleaved measurement while one
  busy-loop process per host CPU runs.  This is the configuration the
  native path exists for (shared Spark executor hosts): pyarrow's
  multithreaded host decode competes for the loaded cores, while the
  native reader's host share is a metadata walk + codec calls.

IO noise is minimized by page-cache residency (a distinct file per rep —
identical repeated device inputs can be served from a cache through the
TPU tunnel, BASELINE.md measurement rule #2).

Run: python benchmarks/bench_parquet.py
"""

from __future__ import annotations

import json
import multiprocessing
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N = 4_000_000
REPS = 5


def _spin():
    while True:
        pass


def _measure(paths, warm_path, read_parquet):
    """Interleaved per-rep samples: {engine: median rows/s}.

    Warm-up reads a SEPARATE scratch file so every timed read is a
    distinct device input (measurement rule #2)."""
    samples = {"native": [], "arrow": []}
    for engine in samples:                      # warm: page cache + jit
        t = read_parquet(warm_path, engine=engine)
        _ = np.asarray(t["i64"].data[-1:])
    for p in paths:
        for engine in samples:
            t0 = time.perf_counter()
            t = read_parquet(p, engine=engine)
            _ = np.asarray(t["i64"].data[-1:])  # fence per sample
            samples[engine].append(N / (time.perf_counter() - t0))
    return {e: statistics.median(v) for e, v in samples.items()}


def main():
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io import read_parquet

    rng = np.random.default_rng(17)
    vocab = np.asarray([f"cat-{i:03d}" for i in range(200)])
    at = pa.table({
        "i64": pa.array(rng.integers(-1 << 40, 1 << 40, N),
                        mask=rng.random(N) < 0.1),
        "f64": rng.normal(size=N),
        "i32": rng.integers(-1 << 20, 1 << 20, N).astype(np.int32),
        "s": pa.array(vocab[rng.integers(0, len(vocab), N)]),
    })

    with tempfile.TemporaryDirectory() as d:
        paths = []
        for r in range(REPS + 1):               # +1: the warm-up scratch
            p = Path(d) / f"bench-{r}.parquet"
            at2 = at.set_column(1, "f64", pa.array(
                np.asarray(at["f64"]) + float(r)))
            pq.write_table(at2, p, compression="snappy",
                           row_group_size=1 << 20)
            paths.append(p)
        warm_path, paths = paths[-1], paths[:-1]

        quiet = _measure(paths, warm_path, read_parquet)
        for engine, v in quiet.items():
            print(json.dumps({"metric": f"parquet_scan_{engine}_4M",
                              "value": round(v, 1), "unit": "rows/sec"}),
                  flush=True)

        ncpu = os.cpu_count() or 8
        ctx = multiprocessing.get_context("spawn")  # fork + JAX threads is UB
        spinners = [ctx.Process(target=_spin, daemon=True)
                    for _ in range(ncpu)]
        for s in spinners:
            s.start()
        try:
            loaded = _measure(paths, warm_path, read_parquet)
        finally:
            for s in spinners:
                s.terminate()
        for engine, v in loaded.items():
            print(json.dumps(
                {"metric": f"parquet_scan_{engine}_4M_contended",
                 "value": round(v, 1), "unit": "rows/sec"}), flush=True)

        bench_stream_scan(warm_path)


def bench_stream_scan(path):
    """File → streaming executor: ``scan_parquet`` row groups drive
    ``run_plan_stream`` (the scan already prefetches, so prefetch=False),
    an aggregation-terminated plan stream-combines on device and
    materializes once at the end."""
    from spark_rapids_tpu.exec import col, plan, run_plan_stream
    from spark_rapids_tpu.io import scan_parquet
    from spark_rapids_tpu.obs import bench_stream_line

    p = (plan()
         .filter(col("i64") > 0)
         .with_columns(bucket=col("i32") % 64)
         .groupby_agg(["bucket"], [("f64", "sum", "f_sum"),
                                   ("f64", "count", "n")],
                      domains={"bucket": (-63, 63)}))
    for _ in run_plan_stream(p, scan_parquet(path, columns=["i64", "i32",
                                                            "f64"])):
        pass                                     # warm compile
    t0 = time.perf_counter()
    for _ in run_plan_stream(p, scan_parquet(path, columns=["i64", "i32",
                                                            "f64"])):
        pass
    dt_s = time.perf_counter() - t0
    print(json.dumps({"metric": "parquet_stream_combine_4M",
                      "value": round(N / dt_s, 1), "unit": "rows/sec"}),
          flush=True)
    print(bench_stream_line(), flush=True)


if __name__ == "__main__":
    main()
