"""Capacity accountant + autoscaling advisor (obs/capacity.py) and its
surfaces (``/capacity``, ``srt_capacity_*`` gauges, ``obs advisor``).

Five contracts:

1. **Pure math** — busy-seconds union-merge (overlaps and the dist
   fan-out count once), Little's-law effective concurrency, nearest-rank
   percentiles, and trend are plain functions over explicit inputs:
   zero-traffic, single-query, and saturated synthetic windows all
   derive well-defined observables.
2. **Deterministic advice with hysteresis** — ``recommend`` is a pure
   ranked mapping of snapshot → evidence-cited actions; ``Advisor``
   surfaces an action only after ``confirm`` consecutive windows and
   clears it only after ``clear`` absent ones, so flapping candidates
   never reach the operator.
3. **Gated feeds** — every ``feed_*`` is a no-op unless ``SRT_METRICS=1``
   and the accountant survives concurrent feeding while being scraped.
4. **Surfaces** — ``/capacity`` serves the advisor payload,
   ``/metrics`` exports ``srt_capacity_*`` gauges and
   ``srt_live_recent_evictions_total``, bundles carry a ``capacity``
   block the doctor renders, and the offline history replay drives the
   same derive/recommend core.
5. **Knob + state hygiene** — the new knobs raise knob-named
   ValueErrors, and ``reset()`` / ``server.reset_histograms()`` give
   back-to-back lanes a clean slate.
"""

import json
import threading
import urllib.request

import pytest

from spark_rapids_tpu import config
from spark_rapids_tpu.obs import capacity
from spark_rapids_tpu.obs import server
from spark_rapids_tpu.obs.metrics import registry


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for knob in ("SRT_CAPACITY_WINDOW_S", "SRT_CAPACITY_TARGETS",
                 "SRT_SERVE_MAX_CONCURRENT", "SRT_SERVE_HBM_BUDGET",
                 "SRT_RESULT_CACHE", "SRT_LIVE_RECENT"):
        monkeypatch.delenv(knob, raising=False)
    capacity.reset()
    registry().reset()
    server.reset_histograms()
    yield
    capacity.reset()
    registry().reset()
    server.reset_histograms()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    yield


@pytest.fixture
def metrics_off(monkeypatch):
    monkeypatch.delenv("SRT_METRICS", raising=False)


def _derive(events, w0=0.0, w1=10.0, max_concurrent=4, hbm_budget=None,
            result_cache_on=False):
    return capacity.derive(events, w0, w1, max_concurrent=max_concurrent,
                           hbm_budget=hbm_budget,
                           result_cache_on=result_cache_on)


# -- pure math ---------------------------------------------------------


def test_merged_busy_counts_overlaps_once():
    # Two workers concurrently busy 1..3 and 2..4: union is 1..4 = 3s,
    # not 4s — this is what keeps busy fraction <= 1 under the dist
    # path's 8-way fan-out of identical spans.
    assert capacity.merged_busy_seconds(
        [(1.0, 3.0), (2.0, 4.0)], 0.0, 10.0) == pytest.approx(3.0)
    # The fan-out case literally: 8 copies of one interval.
    assert capacity.merged_busy_seconds(
        [(1.0, 2.0)] * 8, 0.0, 10.0) == pytest.approx(1.0)


def test_merged_busy_clips_to_window():
    # A span straddling the window start only counts its in-window part.
    assert capacity.merged_busy_seconds(
        [(-5.0, 5.0)], 0.0, 10.0) == pytest.approx(5.0)
    assert capacity.merged_busy_seconds([], 0.0, 10.0) == 0.0


def test_littles_law_effective_concurrency():
    # 4 queries of 5s each inside a 10s window: L = 20/10 = 2 queries
    # concurrently in service on average.
    assert capacity.effective_concurrency(
        [5.0] * 4, 10.0) == pytest.approx(2.0)
    assert capacity.effective_concurrency([], 10.0) == 0.0


def test_percentile_nearest_rank():
    xs = [0.1, 0.2, 0.3, 0.4, 0.5]
    assert capacity.percentile(xs, 50.0) == pytest.approx(0.3)
    assert capacity.percentile(xs, 95.0) == pytest.approx(0.5)
    assert capacity.percentile([], 95.0) is None


def test_trend_is_second_half_minus_first_half():
    rising = [(1.0, 0.1), (2.0, 0.1), (8.0, 0.5), (9.0, 0.5)]
    assert capacity.trend(rising, 0.0, 10.0) == pytest.approx(0.4)
    assert capacity.trend([(1.0, 1.0)], 0.0, 10.0) == 0.0  # one half empty


# -- derive over synthetic windows -------------------------------------


def test_zero_traffic_window_is_well_defined():
    snap = _derive({})
    assert snap["busy"]["dispatch_fraction"] == 0.0
    assert snap["queue"]["waits"] == 0
    assert snap["littles_law"]["effective_concurrency"] == 0.0
    assert snap["littles_law"]["utilization_of_cap"] == 0.0
    assert snap["hbm"]["headroom_fraction"] is None
    assert capacity.recommend(snap) == []


def test_single_query_window():
    events = {
        "dispatch": [(2.0, 5.0)],
        "completions": [(5.0, "table", 4.0, "fpA")],
    }
    snap = _derive(events)
    assert snap["busy"]["dispatch_fraction"] == pytest.approx(0.3)
    assert snap["littles_law"]["completions"] == 1
    assert snap["littles_law"]["effective_concurrency"] == \
        pytest.approx(0.4)
    # One healthy query earns no advice.
    assert capacity.recommend(snap) == []


def test_saturated_window_recommends_raise_workers():
    # Cap of 1 fully utilized, queue backing up, device has headroom.
    events = {
        "dispatch": [(float(i), i + 0.4) for i in range(10)],
        "queue_waits": [(float(i), 0.5 + 0.1 * i) for i in range(10)],
        "queue_depths": [(9.0, 4)],
        "completions": [(float(i), "table", 1.0, f"fp{i}")
                        for i in range(10)],
    }
    snap = _derive(events, max_concurrent=1)
    assert 0.0 < snap["busy"]["dispatch_fraction"] <= 1.0
    assert snap["littles_law"]["utilization_of_cap"] == 1.0
    recs = capacity.recommend(snap)
    actions = [r["action"] for r in recs]
    assert "raise_workers" in actions
    top = recs[actions.index("raise_workers")]
    # Evidence cites the observables that triggered the action.
    assert top["evidence"]["max_concurrent"] == 1
    assert top["evidence"]["queue_waits"] == 10


def test_saturated_device_recommends_shed_load():
    events = {
        "dispatch": [(0.0, 9.9)],
        "queue_waits": [(1.0, 0.3), (2.0, 0.3), (8.0, 1.0), (9.0, 1.2)],
        "queue_depths": [(9.0, 6)],
        "completions": [(9.0, "table", 9.0, "fpA")],
    }
    snap = _derive(events, max_concurrent=1)
    recs = capacity.recommend(snap)
    assert recs and recs[0]["action"] == "shed_load"
    assert recs[0]["severity"] == 90
    # raise_workers must NOT fire when the device itself is the
    # bottleneck.
    assert "raise_workers" not in [r["action"] for r in recs]


def test_admission_pressure_recommends_grow_hbm_budget():
    events = {"admission": [(1.0, "wait", 0), (2.0, "reject", 512)],
              "hbm": [(1.0, 950), (2.0, 980)]}
    snap = _derive(events, hbm_budget=1000)
    assert snap["hbm"]["headroom_fraction"] == pytest.approx(0.02)
    recs = capacity.recommend(snap)
    assert [r["action"] for r in recs] == ["grow_hbm_budget"]
    assert recs[0]["evidence"]["rejected_bytes"] == 512


def test_repeated_plans_without_cache_recommend_result_cache():
    events = {"completions": [(1.0, "table", 0.1, "fpA"),
                              (2.0, "table", 0.1, "fpA"),
                              (3.0, "table", 0.1, "fpB")]}
    snap = _derive(events, result_cache_on=False)
    assert snap["repeated_fingerprints"] == ["fpA"]
    assert "enable_result_cache" in \
        [r["action"] for r in capacity.recommend(snap)]
    # With the cache on the advice disappears.
    snap_on = _derive(events, result_cache_on=True)
    assert "enable_result_cache" not in \
        [r["action"] for r in capacity.recommend(snap_on)]


def test_idle_pool_recommends_lower_workers():
    events = {"dispatch": [(1.0, 1.1)],
              "completions": [(1.1, "table", 0.1, "fpA")]}
    snap = _derive(events, max_concurrent=8)
    recs = capacity.recommend(snap)
    assert [r["action"] for r in recs] == ["lower_workers"]


def test_recommend_is_deterministic_and_ranked():
    events = {
        "dispatch": [(float(i), i + 0.2) for i in range(10)],
        "queue_waits": [(float(i), 0.6) for i in range(10)],
        "queue_depths": [(9.0, 3)],
        "admission": [(5.0, "wait", 0)],
        "completions": [(float(i), "table", 1.0, "fpA")
                        for i in range(10)],
    }
    snap = _derive(events, max_concurrent=1)
    a = capacity.recommend(snap)
    b = capacity.recommend(snap)
    assert a == b
    assert [r["severity"] for r in a] == \
        sorted((r["severity"] for r in a), reverse=True)


def test_targets_override_changes_thresholds():
    events = {"dispatch": [(1.0, 1.1)],
              "completions": [(1.1, "table", 0.1, "fpA")]}
    snap = _derive(events, max_concurrent=8)
    # Idle pool at the defaults → lower_workers; tightening util_low to
    # zero silences it — the targets override is honored.
    assert [r["action"] for r in capacity.recommend(snap)] == \
        ["lower_workers"]
    assert capacity.recommend(snap, {"util_low": 0.0}) == []


# -- hysteresis --------------------------------------------------------


CAND = {"action": "raise_workers", "severity": 80, "reason": "r",
        "evidence": {}}


def test_advisor_confirms_after_n_windows():
    adv = capacity.Advisor(confirm=2, clear=2)
    assert adv.observe([CAND]) == []          # 1st sighting: not yet
    assert adv.observe([CAND]) == [CAND]      # 2nd: confirmed
    assert adv.observe([CAND]) == [CAND]


def test_advisor_flapping_candidate_never_surfaces():
    adv = capacity.Advisor(confirm=2, clear=2)
    for _ in range(6):                        # present, absent, present…
        assert adv.observe([CAND]) == []
        adv.observe([])
    # The absent window resets the streak each time, so a candidate
    # alternating window-to-window is never recommended.


def test_advisor_clears_after_n_quiet_windows():
    adv = capacity.Advisor(confirm=1, clear=2)
    assert adv.observe([CAND]) == [CAND]
    assert adv.observe([]) == [CAND]          # 1 quiet window: sticky
    assert adv.observe([]) == []              # 2nd: cleared
    assert adv.observe([]) == []


def test_verdict_for():
    assert capacity.verdict_for([]) == "healthy"
    assert capacity.verdict_for([CAND]) == "saturated"
    assert capacity.verdict_for(
        [{"action": "grow_hbm_budget", "severity": 70}]) == "pressured"
    assert capacity.verdict_for(
        [{"action": "lower_workers", "severity": 30}]) == "underutilized"


# -- feeds, gating, concurrency ----------------------------------------


def test_feeds_are_noops_when_metrics_off(metrics_off):
    capacity.feed_span("run.dispatch", 0.0, 1e6)
    capacity.feed_queue_wait(1.0)
    capacity.feed_queue_depth(5)
    capacity.feed_admission_wait()
    capacity.feed_admission_reject(100)
    capacity.feed_hbm(100)
    capacity.feed_completion("table", 1.0, "fp")
    snap = capacity.snapshot(window_s=3600)
    assert snap["littles_law"]["completions"] == 0
    assert snap["queue"]["waits"] == 0
    assert snap["busy"]["dispatch_spans"] == 0


def test_feed_span_filters_non_dispatch_names(metrics_on):
    # Feed timestamps share timeline.now_us()'s perf_counter base, so
    # the synthetic spans must be now-relative to land in the window.
    import time
    now_us = time.perf_counter() * 1e6
    capacity.feed_span("scan.parquet", now_us - 2e6, 1e6)  # not metered
    capacity.feed_span("run.dispatch", now_us - 2e6, 1e6)
    capacity.feed_span("stream.materialize", now_us - 2e6, 1e6)
    snap = capacity.snapshot(window_s=3600)
    assert snap["busy"]["dispatch_spans"] == 1
    assert snap["busy"]["materialize_spans"] == 1


def test_feed_span_classifies_combine_path_names(metrics_on):
    # The combine-path dist stream's device walls are named
    # stream.partial / stream.combine / stream.merge_collective, and
    # its device->host wall stream.finalize; backpressure is a wait,
    # not device work, and must stay out of the busy math.
    import time
    now_us = time.perf_counter() * 1e6
    for name in ("stream.partial", "stream.combine",
                 "stream.merge_collective"):
        capacity.feed_span(name, now_us - 5e6, 1e6)
    capacity.feed_span("stream.finalize", now_us - 2e6, 1e6)
    capacity.feed_span("stream.backpressure", now_us - 2e6, 1e6)
    snap = capacity.snapshot(window_s=3600)
    assert snap["busy"]["dispatch_spans"] == 3
    assert snap["busy"]["materialize_spans"] == 1


def test_flight_span_feeds_capacity(metrics_on):
    # The timeline-off serving configuration: spans reach the
    # accountant through the flight recorder's scope path.
    from spark_rapids_tpu.obs import flight, timeline
    with timeline.query_scope(424242):
        span = flight.trace_span("run.dispatch", {})
        assert span is not None
        span.end()
    snap = capacity.snapshot(window_s=3600)
    assert snap["busy"]["dispatch_spans"] == 1


def test_concurrent_feeding_while_scraping(metrics_on):
    # Feeder threads hammer every feed while scrapers render /metrics
    # text and advisor payloads — no exceptions, consistent output.
    stop = threading.Event()
    errors = []

    def feeder():
        i = 0
        while not stop.is_set():
            capacity.feed_span("run.dispatch", i * 1e3, 5e2)
            capacity.feed_queue_wait(0.01)
            capacity.feed_queue_depth(i % 7)
            capacity.feed_hbm(i)
            capacity.feed_completion("table", 0.01, f"fp{i % 3}")
            i += 1

    def scraper():
        # 8 full advise+exposition rounds against 3 hammering feeders is
        # plenty of interleaving; 50 rounds cost ~35s of suite time.
        try:
            for _ in range(8):
                payload = capacity.advise(window_s=5.0)
                assert 0.0 <= payload["snapshot"]["busy"][
                    "dispatch_fraction"] <= 1.0
                text = server.prometheus_text()
                assert "srt_capacity_busy_fraction" in text
        except Exception as exc:       # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=feeder) for _ in range(3)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads[3:]:
        t.join(timeout=60)
    stop.set()
    for t in threads[:3]:
        t.join(timeout=10)
    assert not errors, errors


# -- surfaces ----------------------------------------------------------


def test_capacity_endpoint_and_gauges(metrics_on):
    import time
    capacity.feed_span("run.dispatch",
                       time.perf_counter() * 1e6 - 3e6, 2e6)
    capacity.feed_queue_wait(0.4)
    capacity.feed_completion("table", 0.5, "fpA")
    capacity.feed_completion("table", 0.5, "fpA")
    srv = server.start(port=0)
    try:
        with urllib.request.urlopen(srv.url + "/capacity",
                                    timeout=5) as resp:
            payload = json.loads(resp.read().decode())
        assert set(payload) == {"snapshot", "candidates",
                                "recommendations", "verdict"}
        assert payload["snapshot"]["littles_law"]["completions"] == 2
        with urllib.request.urlopen(srv.url + "/metrics",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert "srt_capacity_busy_fraction" in text
        assert "srt_capacity_effective_concurrency" in text
        assert "# TYPE srt_capacity_busy_fraction gauge" in text
    finally:
        server.stop()


def test_metrics_scrape_does_not_advance_hysteresis(metrics_on):
    # /metrics must be a read-only observer: repeated scrapes never
    # confirm an action into the advisor's stable set.
    capacity.feed_completion("table", 0.1, "fpA")
    capacity.feed_completion("table", 0.1, "fpA")
    for _ in range(5):
        server.prometheus_text()
    payload = capacity.advise(window_s=3600)
    # First real advise(): the enable_result_cache candidate is fresh
    # (streak 1), so it cannot be confirmed yet.
    assert payload["candidates"]
    assert payload["recommendations"] == []


def test_advise_confirms_across_evaluations(metrics_on):
    capacity.feed_completion("table", 0.1, "fpA")
    capacity.feed_completion("table", 0.1, "fpA")
    first = capacity.advise(window_s=3600)
    second = capacity.advise(window_s=3600)
    assert first["recommendations"] == []
    assert "enable_result_cache" in \
        [r["action"] for r in second["recommendations"]]
    assert second["verdict"] == "pressured"


def test_bundle_carries_capacity_block(metrics_on):
    from spark_rapids_tpu.obs import bundle
    capacity.feed_completion("table", 0.1, "fpA")
    payload = bundle.build("failure")
    assert set(payload["capacity"]) == {"snapshot", "recommendations",
                                        "verdict"}
    from spark_rapids_tpu.obs.doctor import diagnose
    report = diagnose(payload)
    assert "verdict" in report          # old bundles (no block) also fine
    assert diagnose({"metric": "postmortem_bundle", "error": {},
                     "recovery": {}, "slo": {}, "metrics": {},
                     "fingerprint": ""})["verdict"]


def test_render_advisor_is_pure():
    from spark_rapids_tpu.obs.__main__ import render_advisor
    payload = {
        "verdict": "saturated",
        "snapshot": _derive({"dispatch": [(0.0, 5.0)]}),
        "candidates": [],
        "recommendations": [dict(CAND, evidence={"busy_fraction": 0.9})],
    }
    out = render_advisor(payload, source="test")
    assert "verdict=saturated" in out
    assert "raise_workers" in out
    assert "busy_fraction=0.9" in out
    empty = render_advisor({"verdict": "healthy", "snapshot": _derive({}),
                            "candidates": [], "recommendations": []})
    assert "none — capacity looks healthy" in empty


def test_offline_history_replay(tmp_path, metrics_on, monkeypatch):
    monkeypatch.setenv("SRT_SERVE_MAX_CONCURRENT", "1")
    path = tmp_path / "hist.jsonl"
    recs = [{"fingerprint": "fpA", "mode": "table", "total_seconds": 1.0,
             "timings": {"execute_seconds": 0.9},
             "serve": {"queue_wait_seconds": 0.5, "admission": "queued"},
             "cost": {"hbm": {"peak_bytes": 1 << 20}}}] * 5
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    from spark_rapids_tpu.obs.__main__ import _advise_history
    payload = _advise_history(str(path), last=256)
    snap = payload["snapshot"]
    assert 0.0 < snap["busy"]["dispatch_fraction"] <= 1.0
    assert snap["littles_law"]["completions"] == 5
    assert payload["recommendations"], payload
    # events_from_history lays records back-to-back: 5 x 1s.
    events, w0, w1 = capacity.events_from_history(recs)
    assert w1 - w0 == pytest.approx(5.0)
    assert len(events["dispatch"]) == 5


# -- satellites: histogram reset + eviction counter --------------------


def test_reset_histograms_isolates_lanes(metrics_on):
    server.observe_hist("query_seconds", 0.5, {"mode": "table"})
    assert "srt_query_seconds_bucket" in "\n".join(server.histogram_text())
    server.reset_histograms()
    # A back-to-back bench lane starts from zero observations.
    assert server.histogram_text() == []
    server.observe_hist("query_seconds", 0.1, {"mode": "table"})
    text = "\n".join(server.histogram_text())
    assert "srt_query_seconds_count" in text
    assert 'srt_query_seconds_count{mode="table"} 1' in text


def test_recent_evictions_counter(metrics_on, monkeypatch):
    from spark_rapids_tpu.obs import live
    monkeypatch.setenv("SRT_LIVE_RECENT", "2")
    live.reset()
    try:
        for i in range(5):
            live.start("table", force=True).finish()
        # 5 finishes with keep=2: 3 evictions counted.
        assert registry().counter("live.recent_evictions").value == 3
        assert "srt_live_recent_evictions_total 3" in \
            server.prometheus_text()
    finally:
        live.reset()


# -- knob hygiene ------------------------------------------------------


def test_capacity_window_knob(monkeypatch):
    assert config.capacity_window_s() == 60.0
    monkeypatch.setenv("SRT_CAPACITY_WINDOW_S", "12.5")
    assert config.capacity_window_s() == 12.5
    monkeypatch.setenv("SRT_CAPACITY_WINDOW_S", "0")
    with pytest.raises(ValueError, match="SRT_CAPACITY_WINDOW_S"):
        config.capacity_window_s()
    monkeypatch.setenv("SRT_CAPACITY_WINDOW_S", "soon")
    with pytest.raises(ValueError, match="SRT_CAPACITY_WINDOW_S"):
        config.capacity_window_s()


def test_capacity_targets_knob(monkeypatch):
    assert config.capacity_targets() == capacity.TARGET_DEFAULTS
    monkeypatch.setenv("SRT_CAPACITY_TARGETS",
                       "busy_high=0.9, wait_s=0.5")
    t = config.capacity_targets()
    assert t["busy_high"] == 0.9 and t["wait_s"] == 0.5
    assert t["busy_low"] == capacity.TARGET_DEFAULTS["busy_low"]
    monkeypatch.setenv("SRT_CAPACITY_TARGETS", "warp_factor=9")
    with pytest.raises(ValueError, match="SRT_CAPACITY_TARGETS"):
        config.capacity_targets()
    monkeypatch.setenv("SRT_CAPACITY_TARGETS", "busy_high=very")
    with pytest.raises(ValueError, match="SRT_CAPACITY_TARGETS"):
        config.capacity_targets()
