"""Postmortem doctor — explain a failed or slow query from its bundle.

``python -m spark_rapids_tpu.obs doctor <bundle.json | fingerprint>``
turns a postmortem bundle (obs/bundle.py) — or, given a bare plan
fingerprint, the newest metrics-history record for it — into a ranked,
human-readable verdict: what failed (the classified error and the
recovery rungs the ladder burned through), and why it was slow (the
cost-ledger bucket that grew, a compile/dict-encode/result-cache hit
rate that collapsed, bucket-pad waste, queue wait) **relative to the
history baseline for the same fingerprint**
(:func:`obs.history.lookup_latest`, ``SRT_METRICS_HISTORY``).

The analysis is pure dict-diffing over persisted JSON: jax-free, no
process state needed, runnable on a laptop against a bundle scp'd out
of an incident.  Findings carry a numeric severity and render
most-damning-first; :func:`diagnose` is the library entry, ``main`` the
CLI (exit 0 whenever a verdict was produced).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

#: A completed query this much slower than its baseline is a finding
#: even without an SLO configured (wall clocks are noisy; 1.5x is not).
SLOWDOWN_MIN_RATIO = 1.5

#: Pad waste beyond this fraction of padded rows earns a finding.
PAD_WASTE_MIN_FRAC = 0.5


def _finding(severity: int, title: str, detail: str) -> Dict[str, Any]:
    return {"severity": severity, "title": title, "detail": detail}


def _ratio(new: float, old: float) -> Optional[float]:
    if old is None or new is None or old <= 0 or new < 0:
        return None
    return new / old


def _error_findings(payload: dict) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    err = payload.get("error") or {}
    rec = payload.get("recovery") or {}
    if err.get("type"):
        site = rec.get("site")
        where = f" at site {site!r}" if site else ""
        out.append(_finding(
            100,
            f"{err.get('category') or 'unclassified'} failure{where}: "
            f"{err['type']}",
            str(err.get("message") or "")))
    steps = rec.get("steps") or []
    if steps:
        out.append(_finding(
            90,
            f"recovery ladder attempted {len(steps)} rung(s) before "
            f"giving up",
            f"rungs: {', '.join(steps)}; retries={rec.get('retries', 0)} "
            f"splits={rec.get('splits', 0)} "
            f"cache_evictions={rec.get('cache_evictions', 0)} "
            f"backoff={rec.get('backoff_seconds', 0.0):.3f}s"))
    if payload.get("reason") == "admission_rejected":
        out.append(_finding(
            95, "rejected at admission (never ran)",
            str(err.get("message") or "estimate exceeded the aggregate "
                "HBM budget (SRT_SERVE_HBM_BUDGET)")))
    return out


def _slo_findings(payload: dict) -> List[Dict[str, Any]]:
    slo = payload.get("slo") or {}
    limit, elapsed = slo.get("slo_ms"), slo.get("elapsed_seconds")
    if limit is not None and elapsed is not None \
            and elapsed * 1000.0 > limit:
        return [_finding(
            85, f"SLO breach: {elapsed * 1e3:.1f}ms against "
                f"SRT_SLO_MS={limit:g}",
            f"the query completed, {elapsed * 1e3 - limit:.1f}ms over "
            f"the latency objective")]
    return []


def _cache_findings(qm: dict, base: Optional[dict]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if qm.get("compile_cache") == "miss":
        extra = ""
        if base is not None and base.get("compile_cache") == "hit":
            extra = " (the baseline run hit)"
        comp = (qm.get("timings") or {}).get("compile_seconds", 0.0)
        out.append(_finding(
            60, f"compile cache miss{extra}",
            f"compile_seconds={comp:.3f} paid on this run; a recurring "
            f"plan should hit the in-process or persistent XLA cache"))
    caches = qm.get("caches") or {}
    hits = caches.get("dict_encode_hits", 0)
    misses = caches.get("dict_encode_misses", 0)
    if hits + misses > 0 and misses > hits:
        out.append(_finding(
            40, f"dictionary-encode cache cold: {misses} miss / "
                f"{hits} hit",
            "string columns re-encoded on device instead of reusing "
            "cached encodings"))
    serve = qm.get("serve") or {}
    if serve.get("result_cache") == "miss" and base is not None \
            and (base.get("serve") or {}).get("result_cache") == "hit":
        out.append(_finding(
            35, "result cache missed where the baseline hit",
            "identical resubmissions normally return cached results"))
    return out


def _cost_findings(qm: dict, base: Optional[dict]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    t = (qm.get("timings") or {}).get("total_seconds")
    bt = (base.get("timings") or {}).get("total_seconds") \
        if base is not None else None
    r = _ratio(t, bt)
    if r is not None and r >= SLOWDOWN_MIN_RATIO:
        out.append(_finding(
            80, f"{r:.1f}x slower than the history baseline",
            f"total_seconds={t:.3f} vs baseline {bt:.3f} for the same "
            f"fingerprint"))
        cost = qm.get("cost") or {}
        bcost = base.get("cost") or {}
        grew = []
        for bucket in ("compute_seconds", "ici_seconds",
                       "host_sync_seconds", "dispatch_overhead_seconds",
                       "unattributed_seconds"):
            d = (cost.get(bucket) or 0.0) - (bcost.get(bucket) or 0.0)
            if d > 0:
                grew.append((d, bucket))
        if grew:
            grew.sort(reverse=True)
            d, bucket = grew[0]
            out.append(_finding(
                70, f"cost ledger: {bucket} grew most (+{d:.3f}s)",
                ", ".join(f"{b} +{x:.3f}s" for x, b in grew)))
    qw = (qm.get("serve") or {}).get("queue_wait_seconds", 0.0)
    if t and qw > 0.25 * t:
        out.append(_finding(
            55, f"queue wait dominated: {qw:.3f}s waiting vs {t:.3f}s "
                f"running",
            "raise SRT_SERVE_MAX_CONCURRENT or spread load; admission "
            "and fairness state is in the bundle's metrics.serve block"))
    counters = qm.get("counters") or {}
    pad = counters.get("plan.bucket.pad_rows", 0)
    total = counters.get("plan.bucket.rows_total", 0)
    if total > 0 and pad / total > PAD_WASTE_MIN_FRAC:
        out.append(_finding(
            45, f"bucket padding wasted {pad / total:.0%} of padded rows",
            f"{pad} pad rows of {total} total; widen SRT_SHAPE_BUCKETS "
            f"growth or batch larger inputs"))
    rec = qm.get("recovery") or {}
    if rec.get("retries") or rec.get("splits"):
        out.append(_finding(
            65, f"recovery work during the run: "
                f"{rec.get('retries', 0)} retries, "
                f"{rec.get('splits', 0)} splits",
            f"backoff={rec.get('backoff_seconds', 0.0):.3f}s, "
            f"cache_evictions={rec.get('cache_evictions', 0)} — HBM "
            f"pressure even though the query completed"))
    spill = rec.get("spill") or {}
    if spill.get("bytes_out", 0) > 0:
        pages_out = spill.get("pages_out", 0)
        pages_in = spill.get("pages_in", 0)
        thrashed = pages_in > pages_out  # some page cycled out AND back >1x
        title = ("this query thrashed the spill cache"
                 if thrashed else
                 "this query ran out-of-core (spill engaged)")
        out.append(_finding(
            70 if thrashed else 55, title,
            f"{spill.get('bytes_out', 0)} bytes paged out over "
            f"{pages_out} pages, {pages_in} paged back in "
            f"({spill.get('files', 0)} spill files, "
            f"page_in={spill.get('page_in_seconds', 0.0):.3f}s) — the "
            f"working set exceeds SRT_SERVE_HBM_BUDGET; grow the budget "
            f"or raise SRT_SPILL_HOST_BYTES to keep pages off disk"))
    return out


def _capacity_findings(bundle: dict) -> List[Dict[str, Any]]:
    """Process-saturation context at the moment of the incident — the
    bundle's ``capacity`` block (obs/capacity.py; absent in pre-v2
    bundles).  A failure under a saturated process reads differently
    from the same failure on an idle one."""
    cap = bundle.get("capacity")
    if not isinstance(cap, dict):
        return []
    out: List[Dict[str, Any]] = []
    for rec in cap.get("recommendations") or []:
        action = rec.get("action", "?")
        ev = rec.get("evidence") or {}
        detail = str(rec.get("reason") or "")
        if ev:
            detail += " — evidence: " + ", ".join(
                f"{k}={ev[k]}" for k in sorted(ev))
        out.append(_finding(
            50, f"capacity advisor ({cap.get('verdict', '?')}): {action}",
            detail))
    return out


def _workload_findings(bundle: dict, qm: dict) -> List[Dict[str, Any]]:
    """Fleet-workload context for this query — the bundle's ``workload``
    block (obs/workload.py; absent in pre-v3 bundles).

    Two signals: (a) this query's cost-dominant step kind is also the
    fleet's #1 hotspot — its slowness is a workload-wide kernel gap, not
    a per-query anomaly; (b) the workload advisor confirmed a
    materialization candidate whose prefix this query's plan carries —
    the incident query is paying for work the fleet keeps repeating."""
    wl = bundle.get("workload")
    if not isinstance(wl, dict):
        return []
    snap = wl.get("snapshot") or {}
    hotspots = snap.get("hotspots") or []
    out: List[Dict[str, Any]] = []
    steps = qm.get("steps") or []
    if hotspots and steps:
        by_kind: Dict[str, float] = {}
        for s in steps:
            if isinstance(s, dict) and s.get("kind"):
                sec = float(s.get("seconds", -1.0) or 0.0)
                by_kind[s["kind"]] = by_kind.get(s["kind"], 0.0) \
                    + max(sec, 0.0)
        if by_kind:
            dominant = max(sorted(by_kind), key=lambda k: by_kind[k])
            top = hotspots[0]
            if dominant == top.get("kind"):
                out.append(_finding(
                    50, f"this query's dominant step kind "
                        f"({dominant!r}) is the fleet's #1 hotspot",
                    f"fleet: {top.get('seconds', 0.0):.3f}s across "
                    f"{top.get('queries', 0)} queries "
                    f"({top.get('share', 0.0):.0%} of attributed step "
                    f"seconds, projected kernel win "
                    f"~{top.get('projected_win_s', 0.0):.3f}s) — a "
                    f"Pallas kernel for this kind helps the whole "
                    f"workload, not just this query"))
    for rec in wl.get("recommendations") or []:
        action = rec.get("action", "?")
        if not str(action).startswith("materialize_subplan:"):
            continue
        ev = rec.get("evidence") or {}
        detail = str(rec.get("reason") or "")
        if ev:
            detail += " — evidence: " + ", ".join(
                f"{k}={ev[k]}" for k in sorted(ev))
        out.append(_finding(
            45, f"workload advisor ({wl.get('verdict', '?')}): {action}",
            detail))
    return out


def _semantic_findings(bundle: dict) -> List[Dict[str, Any]]:
    """Semantic-cache context — the bundle's ``semantic`` block
    (serve/semantic.py; absent in pre-v4 bundles).  The load-bearing
    signal: this query recomputed a subplan prefix the workload advisor
    had *confirmed* as a materialization candidate and the semantic
    cache did not serve it — the failed/slow query paid for work the
    serving layer was supposed to amortize."""
    sem = bundle.get("semantic")
    if not isinstance(sem, dict):
        return []
    if not sem.get("hot_prefix_recompute"):
        return []
    fps = [fp for fp in sem.get("prefix_fingerprints") or [] if fp]
    state = ("SRT_SEMANTIC_CACHE is on but had no materialization to "
             "serve" if sem.get("enabled")
             else "SRT_SEMANTIC_CACHE is off")
    return [_finding(
        60, "query recomputed a hot shared subplan prefix",
        f"the workload advisor confirmed a materialize_subplan "
        f"candidate matching this plan's prefix chain "
        f"({', '.join(fps) or '<unknown>'}) but the query did not use "
        f"a cached materialization — {state}; the semantic subplan "
        f"cache or a registered view (SRT_VIEWS) would absorb this "
        f"recurring work")]


def baseline_for(fingerprint: str,
                 history_path: Optional[str] = None) -> Optional[dict]:
    """The same-fingerprint history baseline (newest measured record)."""
    if not fingerprint:
        return None
    from .history import lookup_latest
    return lookup_latest(fingerprint, path=history_path)


def diagnose(payload: dict, baseline: Optional[dict] = None,
             history_path: Optional[str] = None) -> dict:
    """Rank everything wrong with one bundle payload (or bare
    QueryMetrics record).  Returns ``{"verdict", "fingerprint",
    "baseline_used", "findings"}`` with findings sorted most severe
    first; a clean bill of health is still a verdict."""
    if payload.get("metric") == "postmortem_bundle":
        qm = payload.get("metrics") or {}
        bundle = payload
    else:
        qm = payload                    # a raw history/QueryMetrics record
        bundle = {"reason": None, "error": {}, "recovery": {}, "slo": {}}
    fingerprint = payload.get("fingerprint") or qm.get("fingerprint") or ""
    if baseline is None:
        baseline = baseline_for(fingerprint, history_path)
    # Never let the incident record explain itself: a baseline that IS
    # this query (same query_id) says nothing about what changed.
    if baseline is not None \
            and baseline.get("query_id") == qm.get("query_id"):
        baseline = None
    findings = (_error_findings(bundle) + _slo_findings(bundle)
                + _cache_findings(qm, baseline)
                + _cost_findings(qm, baseline)
                + _capacity_findings(bundle)
                + _workload_findings(bundle, qm)
                + _semantic_findings(bundle))
    findings.sort(key=lambda f: -f["severity"])
    if findings:
        verdict = findings[0]["title"]
    elif baseline is None and not qm:
        verdict = "no metrics in bundle and no history baseline — " \
                  "nothing to diagnose"
    else:
        verdict = "no anomalies: timings, caches, and recovery are in " \
                  "line with the baseline"
    return {"verdict": verdict, "fingerprint": fingerprint,
            "baseline_used": baseline is not None, "findings": findings}


def render(report: dict) -> str:
    """The CLI's human-readable rendering of a :func:`diagnose` report."""
    lines = [f"== Doctor == {report['verdict']}"]
    fp = report.get("fingerprint")
    base = ("history baseline" if report.get("baseline_used")
            else "no history baseline")
    lines.append(f"  fingerprint={fp or '<none>'} ({base})")
    for i, f in enumerate(report["findings"], 1):
        lines.append(f"  {i}. [{f['severity']:>3}] {f['title']}")
        if f["detail"]:
            lines.append(f"       {f['detail']}")
    if not report["findings"]:
        lines.append("  (no findings)")
    return "\n".join(lines)


def main(target: str, history_path: Optional[str] = None) -> int:
    """CLI body: ``target`` is a bundle path or a plan fingerprint.
    Prints the verdict; returns 0 when one was produced, 2 when the
    target could not be resolved."""
    baseline: Optional[dict] = None
    if os.path.exists(target):
        try:
            with open(target) as f:
                payload = json.load(f)
        except (OSError, ValueError) as err:
            print(f"doctor: cannot read bundle {target!r}: {err}")
            return 2
    else:
        # Fingerprint mode: diagnose the plan's NEWEST history record
        # against its best prior run — "why did this get slow".
        from .history import load
        recs = load(target, path=history_path)
        if not recs:
            print(f"doctor: {target!r} is neither a bundle file nor a "
                  f"fingerprint with history records "
                  f"(SRT_METRICS_HISTORY)")
            return 2
        payload = recs[-1]
        prior = [r for r in recs[:-1]
                 if (r.get("timings") or {}).get("total_seconds", 0) > 0]
        if prior:
            baseline = min(
                prior, key=lambda r: r["timings"]["total_seconds"])
    print(render(diagnose(payload, baseline=baseline,
                          history_path=history_path)))
    return 0


__all__ = ["PAD_WASTE_MIN_FRAC", "SLOWDOWN_MIN_RATIO", "baseline_for",
           "diagnose", "main", "render"]
