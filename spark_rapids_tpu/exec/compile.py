"""Plan binder, compiler, and executor.

Turns a :class:`..exec.plan.Plan` plus a bound input :class:`..table.Table`
into ONE jitted XLA program (cached per (plan, input signature)), then
materializes the result with at most one host sync.

Execution state inside the traced program is ``(columns, selection)``:

* ``columns`` — dict of fixed-width :class:`..column.Column` (strings never
  enter the program; see below),
* ``selection`` — optional bool vector marking live rows.  A filter ANDs
  into it; group-by consumes it; sort orders live rows first; only
  materialization compacts.

Strings are handled by *indirection*, the TPU answer to variable-width
data in a static-shape program:

* a string **group-by / sort key** is dictionary-encoded at bind time
  (host-assisted, cached per device buffer) — the program sees INT32
  codes whose order is lexicographic, and materialization decodes;
* a string **payload** is represented by a hidden ``__rowid__`` column;
  ``first``/``last`` aggregate the rowid, and materialization gathers the
  actual strings once, at final (small) sizes.

Group-by strategy (chosen statically at bind time per key set):

* **dense**: every key has a static inclusive (lo, hi) domain — from an
  explicit hint, a bool dtype, a dictionary, or a cached one-sync stats
  probe (:mod:`.stats`) — and the cell-product is ≤
  ``dense_groupby_max_cells``.  Group id = direct cell index; aggregation
  = masked reductions over a (cells, rows) broadcast.  No sort, no sync.
* **sorted**: the general path — one multi-operand ``lax.sort`` clusters
  keys (live rows first), segmented associative scans reduce runs, and
  outputs stay padded at the input length with a live-group selection.

The reference's counterpart machinery is cuDF's hash groupby + Spark's
codegen'd aggregate (capability envelope, SURVEY.md §2.3); both assume
cheap device scatters and cheap host round trips — the two things a TPU
plan must avoid, which is why this file exists.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..column import Column
from ..dtypes import BOOL8, INT32, INT64, DType, TypeId
from ..table import Table
from ..ops.groupby import _agg_out_dtype, _minmax_identity, _sum_dtype
from .expr import Col, evaluate, render
from .plan import (CachedSourceStep, FilterStep, GroupAggStep,
                   JoinShuffledStep, JoinStep, LimitStep, Plan, ProjectStep,
                   SortStep, TopKStep, UnionAllStep, WindowStep)

def _dense_max_cells() -> int:
    """Max dense group-by cells (SRT_DENSE_MAX_CELLS, default 256).
    Aggregation work scales with cells x rows, so past a few hundred
    cells the sorted path wins."""
    from ..config import dense_groupby_max_cells
    return dense_groupby_max_cells()

_ROWID = "__rowid__"

#: Engine-owned hidden plan-state columns (rowid indirection, string-agg
#: surrogates, join rowids, lazy-facade attachments).  Narrow selects
#: preserve exactly these — a USER column that merely starts with "__"
#: is ordinary data and narrows away like any other.
_ENGINE_HIDDEN = re.compile(
    r"^(?:__rowid__$|__valid__:|__codes__:|__strref__:"
    r"|__join\d+__|__sjoin\d+__|__lazy\d+__$)")


def _is_engine_hidden(name: str) -> bool:
    return bool(_ENGINE_HIDDEN.match(name))


def _pruned_input(plan: Plan, table: Table) -> Table:
    """Subset the input to an optimizer-pruned plan's live column set
    BEFORE padding/encoding, so pruned payload columns are never bound.
    Identity when the plan was not optimizer-narrowed or nothing drops;
    idempotent, so ``_bind`` (ahead of the bucketing pad) and ``_Bound``
    (direct exact-shape binds) may both call it."""
    if getattr(plan, "opt", None) is None:
        return table
    from .optimize import live_input_names
    live = live_input_names(plan)
    if live is None:
        return table
    live = set(live)
    keep = [nm for nm in table.names
            if nm in live or _is_engine_hidden(nm)]
    if len(keep) == len(table.names):
        return table
    from ..obs.metrics import counter
    counter("plan.opt.pruned_columns").inc(len(table.names) - len(keep))
    return table.select(keep)


class _JoinMarkerT:
    """Data-free stand-in for JoinStep in compiled-program assembly."""
    def __repr__(self):
        return "<join>"


_JOIN_MARKER = _JoinMarkerT()


class _UnionMarkerT:
    """Data-free stand-in for UnionAllStep in compiled-program assembly."""
    def __repr__(self):
        return "<union>"


_UNION_MARKER = _UnionMarkerT()


# ---------------------------------------------------------------------------
# bind-time metadata
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _KeyMeta:
    """Static description of one group-by key at its step."""
    name: str
    lo: int                      # inclusive; 0 for dict codes
    hi: int                      # inclusive
    nullable: bool
    #: dictionary tuple for string keys (None for numeric); static so it can
    #: key the compile cache, used only at materialization.
    dictionary: Optional[tuple[str, ...]]
    dtype: DType


@dataclass(frozen=True)
class _GroupMeta:
    dense: bool
    keys: tuple[_KeyMeta, ...]
    #: cells per key (dense): domain size + null slot.
    sizes: tuple[int, ...]
    cells: int


@dataclass(frozen=True)
class _UnionMeta:
    """Static description of one UNION ALL branch (part of the
    compile-cache key; like :func:`_Bound.assembly_steps` it must not pin
    the branch table's device buffers)."""
    index: int
    steps: tuple                     # branch assembly steps (markers)
    group_metas: tuple
    join_metas: tuple
    union_metas: tuple               # nested unions inside the branch
    n: int                           # branch input rows
    exec_names: tuple[str, ...]      # branch program inputs
    side_names: tuple[str, ...]      # branch side inputs


@dataclass(frozen=True)
class _ColInfo:
    """Static per-column signature of the bound input."""
    name: str
    type_id: int
    scale: int
    nullable: bool
    string: bool


def _dict_encode_cached(col: Column) -> tuple[Column, tuple[str, ...]]:
    """Buffer-identity-memoized dictionary encode, shared with the eager
    string predicates (ops.strings.dictionary_encode_cached)."""
    from ..ops.strings import dictionary_encode_cached
    return dictionary_encode_cached(col)


# ---------------------------------------------------------------------------
# binder
# ---------------------------------------------------------------------------

class _Bound:
    """Everything needed to run a plan against one input signature."""

    def __init__(self, plan: Plan, table: Table, probe_mask=None,
                 init_sel=None, logical_rows=None):
        table = _pruned_input(plan, table)
        self.plan = plan
        self.n = table.num_rows
        self.input_names = tuple(table.names)
        #: restricts stats probes to live rows (a DistTable's row mask —
        #: zero-filled padding slots must not widen key domains)
        self.probe_mask = probe_mask
        #: bind-time live-row selection (shape bucketing: the input was
        #: padded to a bucket capacity and only the leading logical rows
        #: are real) — passed as the program's initial selection so every
        #: row count in the bucket shares one compiled program.
        self.init_sel = init_sel
        #: the caller's pre-padding row count (== n for exact-shape binds)
        self.logical_rows = self.n if logical_rows is None else logical_rows
        self.exec_cols: dict[str, Column] = {}   # traced program inputs
        #: non-row-aligned program inputs (join probe structures, build-side
        #: payload columns) — kept out of the row-state dict so row-wise
        #: steps (sort/limit) never touch them.
        self.side_inputs: dict[str, Column] = {}
        self.string_cols: dict[str, Column] = {} # gathered at materialize
        self.dictionaries: dict[str, tuple[str, ...]] = {}
        #: input string columns not yet shadowed by a project — the set
        #: string-literal predicates may be rewritten against.
        self._live_strcols: set[str] = set()
        #: dictionary-encoded key columns still holding their codes (a
        #: project redefining the name drops it — the vocabulary no
        #: longer describes the values).
        self._live_dictkeys: set[str] = set()
        #: string-valued names produced inside the plan (join string
        #: payloads, first/last string aggregates) — carried by rowid
        #: indirection, so expressions cannot touch them.
        self._deferred_strs: set[str] = set()
        #: hidden join-rowid column -> [(build string Column, out name)]
        self.join_string_srcs: dict[str, list] = {}
        #: state column -> (source Column, forced_nullable) for group-key
        #: domain probing: join payloads map to their (small) build-side
        #: column so the stats probe stays cheap and dense grouping works
        #: on joined keys; left joins force the null slot.
        self.probe_sources: dict[str, tuple[Column, bool]] = {}
        #: plan steps with string aggregations rewritten to rowid/validity
        #: surrogates (what the traced program actually executes).
        self.steps: tuple = ()
        self.group_metas: list[_GroupMeta] = []
        self.join_metas: list = []
        self.union_metas: list[_UnionMeta] = []
        #: the bound input table (shuffled-join bind-time probes read the
        #: original key columns from it)
        self._table = table
        #: True while program row state is still index-aligned with the
        #: input table (no reorder/expansion yet) — the precondition for
        #: binding a shuffled join's per-row probe arrays.
        self._row_aligned = True
        self._passthrough: set[str] = set()
        self._build(table)

    def shuffle_key_source(self, name: str):
        """The input-table column behind ``name`` if it is still
        unmodified and row-aligned, else None."""
        if not self._row_aligned or name not in self._passthrough:
            return None
        return self._table[name] if name in self._table else None

    def _build(self, table: Table) -> None:
        plan = self.plan
        # Which input string columns are used as group/sort keys? They get
        # dictionary codes; other strings ride as rowid indirection.
        key_names: set[str] = set()
        for step in plan.steps:
            if isinstance(step, GroupAggStep):
                key_names.update(step.keys)
            elif isinstance(step, (SortStep, TopKStep)):
                key_names.update(step.by)
            elif isinstance(step, WindowStep):
                key_names.update(step.partition_by)
                key_names.update(step.order_by)

        need_rowid = False
        for name, c in table.items():
            if c.dtype.is_two_word:
                raise TypeError(
                    f"decimal128 column {name!r} is not yet supported in "
                    f"compiled plans (its (n, 2)-word representation cannot "
                    f"ride the 1-D sort/window payload paths); use the "
                    f"eager ops layer, or cast to decimal64/float64 first")
            if c.dtype.is_nested:
                raise TypeError(
                    f"nested column {name!r} ({c.dtype.type_id.name}) is not "
                    f"supported in compiled plans; use the eager ops layer, "
                    f"select struct fields with .field(), or drop the column "
                    f"from the input table first (table.select/.drop — a "
                    f"plan-level select cannot help; this check covers the "
                    f"whole bound input)")
            if c.offsets is None:
                self.exec_cols[name] = c
                continue
            if name in key_names:
                codes, uniq = _dict_encode_cached(c)
                self.exec_cols[name] = codes
                self.dictionaries[name] = uniq
            else:
                self.string_cols[name] = c
                need_rowid = True
        if need_rowid:
            self.exec_cols[_ROWID] = Column(
                data=jnp.arange(self.n, dtype=jnp.int32), dtype=INT32)
        self._live_strcols = set(self.string_cols)
        self._live_dictkeys = set(self.dictionaries)

        # Rewrite string aggregations and track which state columns still
        # hold unchanged input values (so group-key domains may be probed
        # from the input table).
        passthrough: set[str] = set(self.exec_cols)
        current_names = list(self.exec_cols) + list(self.string_cols)
        steps: list = []
        for step in plan.steps:
            step = self._rewrite_string_predicates(step)
            self._check_string_refs(step)
            if isinstance(step, ProjectStep):
                redefined = {nm for nm, e in step.cols
                             if not (isinstance(e, Col) and e.name == nm)}
                passthrough -= redefined
                self._live_strcols -= redefined
                self._live_dictkeys -= redefined
                self._deferred_strs -= redefined
                for nm in redefined:
                    self.probe_sources.pop(nm, None)
                if step.narrow:
                    passthrough &= ({nm for nm, _ in step.cols} | {_ROWID})
                    kept = {nm for nm, _ in step.cols}
                    self._live_strcols &= kept
                    self._live_dictkeys &= kept
                    self._deferred_strs &= kept
                    self.probe_sources = {
                        k: v for k, v in self.probe_sources.items()
                        if k in kept}
                    current_names = [nm for nm, _ in step.cols]
                else:
                    for nm, _ in step.cols:
                        if nm not in current_names:
                            current_names.append(nm)
                steps.append(step)
            elif isinstance(step, GroupAggStep):
                step = self._rewrite_string_aggs(step)
                self.group_metas.append(
                    self._group_meta(step, table, passthrough))
                steps.append(step)
                # After a grouping-sets step a key column may be null at
                # rolled-up levels, so its input-column metadata no longer
                # describes it — keep nothing bind-time-known.
                passthrough = set() if step.sets is not None \
                    else set(step.keys)
                self.probe_sources = {}
                self._row_aligned = False
                self._live_strcols = set()
                # An aggregate over a dict-encoded string column yields
                # codes from the same vocabulary when the agg is order/
                # value-preserving — carry the vocabulary to the output
                # name so materialization decodes it.  Arithmetic aggs
                # over codes would be meaningless numbers; reject them.
                agg_dicts: dict[str, tuple[str, ...]] = {}
                for val, how, out in step.aggs:
                    if val in self._live_dictkeys:
                        if how in ("min", "max", "first", "last"):
                            agg_dicts[out] = self.dictionaries[val]
                        elif how not in ("count", "count_all", "nunique"):
                            raise TypeError(
                                f"aggregation {how!r} is not defined for "
                                f"string column {val!r}")
                self._live_dictkeys &= set(step.keys)
                self.dictionaries.update(agg_dicts)
                self._live_dictkeys |= set(agg_dicts)
                # first/last string aggregates surface as user-visible
                # string outputs backed by __strref__ surrogates (the
                # rewritten agg's out name is "__strref__:<src>:<user>").
                self._deferred_strs = {
                    out.split(":", 2)[2] for _, _, out in step.aggs
                    if out.startswith("__strref__:")}
                current_names = (list(step.keys)
                                 + [out for _, _, out in step.aggs])
                if step.sets is not None:
                    current_names.append(step.grouping_id)
            elif isinstance(step, WindowStep):
                if step.value is not None and (
                        step.value in self.string_cols
                        or step.value in self.dictionaries):
                    raise TypeError(
                        f"window function over string column "
                        f"{step.value!r} is not supported")
                if step.out in current_names:
                    passthrough.discard(step.out)
                    self.probe_sources.pop(step.out, None)
                else:
                    current_names.append(step.out)
                steps.append(step)
            elif isinstance(step, JoinStep):
                from .join import bind_join
                meta = bind_join(self, step, len(self.join_metas),
                                 current_names)
                self.join_metas.append(meta)
                for side_name, out in meta.pays:
                    self.probe_sources[out] = (
                        self.side_inputs[side_name], step.how == "left")
                current_names += [out for _, out in meta.pays]
                current_names += [out for _, out in meta.str_pays]
                self._deferred_strs |= {out for _, out in meta.str_pays}
                steps.append(step)
            elif isinstance(step, JoinShuffledStep):
                if not self._row_aligned:
                    raise TypeError(
                        "a shuffled join must come before any group-by, "
                        "sort, limit, or other shuffled join (its bind-time "
                        "probe is aligned to input-table rows); join first, "
                        "then aggregate")
                from .join import bind_join_shuffled
                self._passthrough = passthrough
                meta = bind_join_shuffled(self, step, len(self.join_metas),
                                          current_names)
                self.join_metas.append(meta)
                steps.append(step)
                if step.how in ("inner", "left"):
                    # Row state is replaced by the expansion: nothing stays
                    # index-aligned with the input, but every gathered
                    # column's value domain is a subset of its source's —
                    # keep dense group-by viable on post-join keys by
                    # probing the sources.
                    for nm in list(passthrough):
                        if nm in table and nm not in self.probe_sources:
                            self.probe_sources[nm] = (table[nm], False)
                    for _, out in meta.pays:
                        src = step.table[out]
                        self.probe_sources[out] = (src, step.how == "left")
                    passthrough = set()
                    self._row_aligned = False
                    current_names += [out for _, out in meta.pays]
                    current_names += [out for _, out in meta.str_pays]
                    self._deferred_strs |= {out for _, out in meta.str_pays}
            elif isinstance(step, UnionAllStep):
                meta, branch = self._bind_union(step, len(self.union_metas),
                                                current_names)
                self.union_metas.append(meta)
                steps.append(step)
                # Post-union state: rows are no longer aligned with the
                # input table; dense group-bys on post-union keys stay
                # possible by probing BOTH sides' bind-time sources.
                merged: dict[str, tuple] = {}
                for nm in current_names:
                    if _is_engine_hidden(nm):
                        continue
                    mine = None
                    if nm in table and nm in passthrough:
                        mine = (table[nm], False)
                    elif nm in self.probe_sources:
                        mine = self.probe_sources[nm]
                    theirs = None
                    if nm in branch._table and nm in branch._passthrough:
                        theirs = (branch._table[nm], False)
                    elif nm in branch.probe_sources:
                        theirs = branch.probe_sources[nm]
                    if mine is not None and theirs is not None:
                        srcs = (mine[0] if isinstance(mine[0], tuple)
                                else (mine[0],))
                        srcs += (theirs[0] if isinstance(theirs[0], tuple)
                                 else (theirs[0],))
                        merged[nm] = (srcs, mine[1] or theirs[1])
                self.probe_sources = merged
                passthrough = set()
                self._row_aligned = False
            else:
                if isinstance(step, (SortStep, LimitStep, TopKStep)):
                    self._row_aligned = False
                steps.append(step)
        self.steps = tuple(steps)
        self._passthrough = passthrough
        # Materialization decodes by name (_rebuild); a vocabulary whose
        # key name was redefined mid-plan must not survive to decode the
        # redefined values as if they were codes.
        self.dictionaries = {k: v for k, v in self.dictionaries.items()
                             if k in self._live_dictkeys}

    def _ensure_pred_codes(self, name: str) -> tuple[str, tuple[str, ...]]:
        """Dictionary-encode string column ``name`` for predicate use and
        return (codes exec-column name, sorted vocabulary).

        A string *group/sort key* already lives in exec state as codes
        under its own name (with its vocabulary in ``self.dictionaries``);
        other string columns get a hidden ``__codes__:`` surrogate."""
        if name in self.dictionaries:
            return name, self.dictionaries[name]
        surrogate = f"__codes__:{name}"
        codes, uniq = _dict_encode_cached(self.string_cols[name])
        if surrogate not in self.exec_cols:
            self.exec_cols[surrogate] = codes
        return surrogate, uniq

    def _rewrite_string_predicates(self, step):
        """Rewrite string-literal predicates onto dictionary codes.

        ``col("ch").eq("web")``, ``.isin(...)``, ordered compares, and
        null tests against *input* string columns become INT32 code
        predicates at bind time: the vocabulary from the cached
        dictionary encode is sorted, so ``code OP bisect(lit)`` preserves
        lexicographic semantics, and the codes column carries the source
        validity so null propagation is unchanged.  Strings themselves
        still never enter the traced program."""
        import bisect

        from .expr import (BinOp, CaseWhen, Cast, Col, Expr, FillNull, IsIn,
                           Lit, UnOp)

        # Rewritable names: live (not yet redefined) input string columns,
        # plus string group/sort keys still riding as codes under their
        # own name (a project redefining the name drops it from both).
        strcols = self._live_strcols | self._live_dictkeys

        def always_false(codes_name: str) -> Expr:
            # ne(c, c): False where valid, null where null.
            return BinOp("ne", Col(codes_name), Col(codes_name))

        def always_true(codes_name: str) -> Expr:
            return BinOp("eq", Col(codes_name), Col(codes_name))

        def cmp(name: str, op: str, value: str) -> Expr:
            from ..ops.strings import scalar_cut
            codes_name, uniq = self._ensure_pred_codes(name)
            kind, k = scalar_cut(op, value, uniq)
            if kind == "const":
                return (always_true(codes_name) if k
                        else always_false(codes_name))
            return BinOp(kind, Col(codes_name), Lit(k))

        from .expr import FLIP_CMP as _FLIP

        def rw(e: Expr) -> Expr:
            if isinstance(e, BinOp):
                l, r = e.left, e.right
                if (isinstance(l, Col) and l.name in strcols
                        and isinstance(r, Lit) and isinstance(r.value, str)):
                    return cmp(l.name, e.op, r.value)
                if (isinstance(r, Col) and r.name in strcols
                        and isinstance(l, Lit) and isinstance(l.value, str)):
                    return cmp(r.name, _FLIP.get(e.op, e.op), l.value)
                return BinOp(e.op, rw(l), rw(r))
            if isinstance(e, IsIn):
                if (isinstance(e.operand, Col) and e.operand.name in strcols
                        and all(isinstance(v, str) for v in e.values)):
                    codes_name, uniq = self._ensure_pred_codes(e.operand.name)
                    idxs = []
                    for v in e.values:
                        i = bisect.bisect_left(uniq, v)
                        if i < len(uniq) and uniq[i] == v:
                            idxs.append(i)
                    if not idxs:
                        return always_false(codes_name)
                    return IsIn(Col(codes_name), tuple(sorted(idxs)))
                return IsIn(rw(e.operand), e.values)
            if isinstance(e, UnOp):
                if (e.op in ("is_null", "is_valid")
                        and isinstance(e.operand, Col)
                        and e.operand.name in strcols):
                    codes_name, _ = self._ensure_pred_codes(e.operand.name)
                    return UnOp(e.op, Col(codes_name))
                return UnOp(e.op, rw(e.operand))
            if isinstance(e, FillNull):
                return FillNull(rw(e.operand), e.value)
            if isinstance(e, Cast):
                return Cast(rw(e.operand), e.to)
            if isinstance(e, CaseWhen):
                branches = tuple((rw(c), rw(v)) for c, v in e.branches)
                default = None if e.default is None else rw(e.default)
                return CaseWhen(branches, default)
            return e

        if isinstance(step, FilterStep):
            return FilterStep(rw(step.pred))
        if isinstance(step, ProjectStep):
            cols = tuple((nm, e if (isinstance(e, Col) and e.name == nm)
                          else rw(e))
                         for nm, e in step.cols)
            return ProjectStep(cols, step.narrow)
        return step

    def _check_string_refs(self, step) -> None:
        """String columns never enter the traced program, so expressions
        may not reference them — except a bare passthrough select (the
        rowid indirection carries those)."""
        from .expr import references
        exprs = []
        if isinstance(step, FilterStep):
            exprs = [step.pred]
        elif isinstance(step, ProjectStep):
            exprs = [e for nm, e in step.cols
                     if not (isinstance(e, Col) and e.name == nm)]
        for e in exprs:
            # Live sets, not all input string names: a project may have
            # legitimately redefined a string name to a numeric column.
            bad = references(e) & (self._live_strcols | self._deferred_strs)
            if bad:
                raise TypeError(
                    f"string column(s) {sorted(bad)} cannot be used in plan "
                    f"expressions (strings pass through plans by indirection; "
                    f"only literal predicates on input string columns rewrite "
                    f"onto dictionary codes — compute other string "
                    f"expressions eagerly with ops.strings, or filter the "
                    f"build table before the join)")

    def _rewrite_string_aggs(self, step: GroupAggStep) -> GroupAggStep:
        """String value columns can't flow through the program; rewrite
        their aggregations onto fixed-width surrogates."""
        new_aggs: list[tuple[str, str, str]] = []
        changed = False
        for value_name, how, out_name in step.aggs:
            if value_name not in self.string_cols:
                new_aggs.append((value_name, how, out_name))
                continue
            changed = True
            src = self.string_cols[value_name]
            if how in ("first", "last"):
                if _ROWID not in self.exec_cols:
                    self.exec_cols[_ROWID] = Column(
                        data=jnp.arange(self.n, dtype=jnp.int32), dtype=INT32)
                new_aggs.append(
                    (_ROWID, how, f"__strref__:{value_name}:{out_name}"))
            elif how in ("count", "count_all"):
                surrogate = f"__valid__:{value_name}"
                if surrogate not in self.exec_cols:
                    self.exec_cols[surrogate] = Column(
                        data=src.valid_mask().astype(jnp.int8),
                        validity=src.validity, dtype=DType(TypeId.INT8))
                new_aggs.append((surrogate, how, out_name))
            elif how == "nunique":
                # Distinct strings == distinct dictionary codes.
                surrogate = f"__codes__:{value_name}"
                if surrogate not in self.exec_cols:
                    codes, _uniq = _dict_encode_cached(src)
                    self.exec_cols[surrogate] = codes
                new_aggs.append((surrogate, how, out_name))
            else:
                raise TypeError(
                    f"aggregation {how!r} is not defined for strings "
                    f"(column {value_name!r})")
        if not changed:
            return step
        return GroupAggStep(step.keys, tuple(new_aggs), step.domains,
                            step.sets, step.grouping_id)

    def _bind_union(self, step: UnionAllStep, index: int,
                    current_names: list[str]):
        """Bind a UNION ALL branch: recursively bind its plan over its
        table, register the branch's program/side inputs under a
        ``__union{i}__:`` prefix, and emit the static meta.  Returns
        ``(meta, branch_bound)`` — the bound branch is used at bind time
        only (probe-source merging); the meta carries no device buffers."""
        if self.string_cols or self.dictionaries or self._deferred_strs:
            raise TypeError(
                "union_all over string-carrying state is not supported "
                "(dictionary codes from two binds don't share a "
                "vocabulary); drop/aggregate the string columns first or "
                "use ops.concat_tables + a fresh plan")
        tbl = step.table
        if tbl.num_rows == 0:
            raise ValueError(
                "union_all branch table has no rows; drop the branch "
                "(XLA programs need non-degenerate static shapes)")
        branch = _Bound(step.plan, tbl)
        if branch.string_cols or branch.dictionaries \
                or branch._deferred_strs:
            raise TypeError(
                "union_all branch carries string columns; aggregate or "
                "drop them in the branch plan first")
        visible = {nm for nm in current_names if not _is_engine_hidden(nm)}
        b_order = _final_order(step.plan.steps, branch.input_names)
        b_visible = {nm for nm in b_order if not _is_engine_hidden(nm)}
        if visible != b_visible:
            raise TypeError(
                f"union_all schema mismatch: state has "
                f"{sorted(visible)}, branch produces {sorted(b_visible)}")
        prefix = f"__union{index}__:"
        for nm, c in branch.exec_cols.items():
            self.side_inputs[prefix + nm] = c
        for nm, c in branch.side_inputs.items():
            self.side_inputs[prefix + "side:" + nm] = c
        meta = _UnionMeta(index, branch.assembly_steps(),
                          tuple(branch.group_metas),
                          tuple(branch.join_metas),
                          tuple(branch.union_metas), branch.n,
                          tuple(branch.exec_cols),
                          tuple(branch.side_inputs))
        return meta, branch

    def _group_meta(self, step: GroupAggStep, table: Table,
                    passthrough: set[str]) -> _GroupMeta:
        from .stats import column_int_range
        keys: list[_KeyMeta] = []
        # nunique/median need their own (keys, value) sort order; the
        # sorted path hosts them.
        dense = not any(how in ("nunique", "median")
                        for _, how, _ in step.aggs)
        sizes: list[int] = []
        for name, hint in zip(step.keys, step.domains):
            # A vocabulary only describes the key while the name still
            # holds its codes (a project may have redefined it).
            dictionary = (self.dictionaries.get(name)
                          if name in self._live_dictkeys else None)
            # Metadata may only come from a bind-time-known source: an
            # unchanged input column, or a join payload's (small)
            # build-side column.  A redefined key's nullability/dtype are
            # unknown at bind time (nullable=True is the safe superset:
            # the null slot just stays empty).
            if name in table and name in passthrough:
                src, forced_null = table[name], False
            elif name in self.probe_sources:
                src, forced_null = self.probe_sources[name]
            else:
                src, forced_null = None, True
            # Post-union probe sources are tuples (one per union side):
            # domains combine as the union of per-source ranges.
            srcs = (src if isinstance(src, tuple)
                    else (src,) if src is not None else ())
            src = srcs[0] if srcs else None
            col = self.exec_cols.get(name) if name in passthrough else None
            if col is not None:
                nullable = col.validity is not None
            elif srcs:
                nullable = forced_null or any(
                    s.validity is not None for s in srcs)
            else:
                nullable = True
            dtype = (col or src).dtype if (col or src) is not None else INT64
            lo = hi = 0
            if dictionary is not None and name in passthrough:
                lo, hi = 0, max(len(dictionary) - 1, 0)
            elif hint is not None:
                lo, hi = hint
            elif srcs and all(s.dtype == BOOL8 for s in srcs):
                lo, hi = 0, 1
            elif (dense and srcs
                  and all(s.offsets is None and s.dtype.is_integer
                          and not s.dtype.is_decimal
                          and not s.dtype.is_timestamp for s in srcs)):
                # Probe only while dense is still possible — each first
                # probe is a blocking host sync.
                rngs = []
                for s in srcs:
                    mask = (self.probe_mask
                            if len(srcs) == 1 and s.size == self.n
                            and self.probe_mask is not None else None)
                    rngs.append(column_int_range(s, extra_mask=mask))
                if any(r is None for r in rngs):
                    dense = False
                else:
                    lo = min(r[0] for r in rngs)
                    hi = max(r[1] for r in rngs)
                    if hi - lo + 1 > _dense_max_cells():
                        dense = False
            else:
                dense = False
            size = (hi - lo + 1) + (1 if nullable else 0)
            sizes.append(size)
            keys.append(_KeyMeta(name, lo, hi, nullable, dictionary, dtype))
        cells = 1
        for s in sizes:
            cells *= s
        if cells > _dense_max_cells():
            dense = False
        return _GroupMeta(dense, tuple(keys), tuple(sizes), cells)

    def assembly_steps(self) -> tuple:
        """Steps with JoinStep/UnionAllStep replaced by data-free markers:
        the traced program reads everything it needs from the side inputs
        and the static metas, so neither the compile-cache key nor the
        compiled closure may pin the build/branch Tables' device buffers
        (two tables with identical signatures correctly share one
        program)."""
        out = []
        for s in self.steps:
            if isinstance(s, (JoinStep, JoinShuffledStep)):
                out.append(_JOIN_MARKER)
            elif isinstance(s, UnionAllStep):
                out.append(_UNION_MARKER)
            else:
                out.append(s)
        return tuple(out)

    def signature(self):
        cols = tuple(_ColInfo(n, int(c.dtype.type_id), c.dtype.scale,
                              c.validity is not None, c.offsets is not None)
                     for n, c in self.exec_cols.items())
        side = tuple((n, int(c.dtype.type_id), int(c.data.shape[0]),
                      c.validity is not None)
                     for n, c in self.side_inputs.items())
        # The bucketed flag keeps the counters honest when bucketed and
        # exact-shape binds of the same capacity coexist in one process
        # (the program is invoked with a different arity in each mode, so
        # jit would compile twice behind one cache entry otherwise).
        return (self.assembly_steps(), self.n, cols, side,
                tuple(self.group_metas), tuple(self.join_metas),
                tuple(self.union_metas), self.init_sel is not None)


# ---------------------------------------------------------------------------
# traced step kernels
# ---------------------------------------------------------------------------

def _trace_filter(cols, sel, step: FilterStep):
    pred = evaluate(step.pred, cols)
    keep = pred.data.astype(jnp.bool_)
    if pred.validity is not None:
        keep = keep & pred.validity
    return cols, keep if sel is None else (sel & keep)


def lit_column(value, n: int) -> Column:
    """Broadcast a bare scalar literal to an ``n``-row constant column
    (Spark ``lit()``); the dtype follows the Python type."""
    if isinstance(value, bool):
        return Column(data=jnp.full(n, value, jnp.uint8), dtype=BOOL8)
    if isinstance(value, int):
        return Column(data=jnp.full(n, value, jnp.int64), dtype=INT64)
    if isinstance(value, float):
        from ..dtypes import FLOAT64
        return Column(data=jnp.full(n, value, jnp.float64), dtype=FLOAT64)
    raise TypeError(
        f"cannot project literal {value!r} as a column (bool/int/float "
        f"literals broadcast; strings cannot enter a traced program)")


def _trace_project(cols, sel, step: ProjectStep):
    new = dict(cols) if not step.narrow else {}
    if step.narrow:
        # Hidden engine columns (rowid indirection, string-agg surrogates,
        # join rowids, lazy attachments) always survive narrowing — they
        # carry state the user-visible schema doesn't show.
        for nm in cols:
            if _is_engine_hidden(nm):
                new[nm] = cols[nm]
    n = next(iter(cols.values())).size
    for name, e in step.cols:
        if isinstance(e, Col) and e.name == name and name not in cols:
            continue          # deferred string passthrough (rowid-carried)
        out = evaluate(e, cols)
        if not isinstance(out, Column):       # bare literal select
            out = lit_column(out, n)
        new[name] = out
    return new, sel


def _trace_sort(cols, sel, step: SortStep):
    from ..ops.sort import sort_operands
    n = next(iter(cols.values())).size
    key_cols = [cols[k] for k in step.by]
    ops_list = sort_operands(key_cols, list(step.ascending),
                             list(step.nulls_first))
    if sel is not None:
        ops_list = [jnp.where(sel, jnp.uint8(0), jnp.uint8(1))] + ops_list
    payload: list[jax.Array] = []
    layout: list[tuple[str, bool]] = []      # (name, has_validity)
    for name, c in cols.items():
        payload.append(c.data)
        has_v = c.validity is not None
        if has_v:
            payload.append(c.validity)
        layout.append((name, has_v))
    if sel is not None:
        payload.append(sel)
    sorted_all = jax.lax.sort(ops_list + payload, dimension=0,
                              is_stable=True, num_keys=len(ops_list))
    rest = list(sorted_all[len(ops_list):])
    out: dict[str, Column] = {}
    i = 0
    for name, has_v in layout:
        d = rest[i]; i += 1
        v = None
        if has_v:
            v = rest[i]; i += 1
        out[name] = Column(data=d, validity=v, dtype=cols[name].dtype)
    new_sel = rest[i] if sel is not None else None
    return out, new_sel


def _trace_limit(cols, sel, step: LimitStep):
    n = next(iter(cols.values())).size
    k = min(step.k, n)
    if sel is not None:
        # Compact live rows to the front (stable), then take k.
        order = jnp.argsort(~sel, stable=True)
        idx = order[:k]
        out = {name: Column(data=jnp.take(c.data, idx),
                            validity=None if c.validity is None
                            else jnp.take(c.validity, idx),
                            dtype=c.dtype)
               for name, c in cols.items()}
        return out, jnp.take(sel, idx)
    out = {name: Column(data=c.data[:k],
                        validity=None if c.validity is None else c.validity[:k],
                        dtype=c.dtype)
           for name, c in cols.items()}
    return out, None


def _trace_topk(cols, sel, step: TopKStep):
    """Fused Sort→Limit(k) (the optimizer's ``topk`` rewrite): the
    selection-leading stable sort already puts live rows first, so the
    leading ``k`` slots are exactly what :func:`_trace_limit`'s
    stable-argsort-and-gather would pick — a static slice replaces the
    limit's second full-length sort pass."""
    out, new_sel = _trace_sort(
        cols, sel, SortStep(step.by, step.ascending, step.nulls_first))
    n = next(iter(out.values())).size
    k = min(step.k, n)
    sliced = {name: Column(data=c.data[:k],
                           validity=None if c.validity is None
                           else c.validity[:k],
                           dtype=c.dtype)
              for name, c in out.items()}
    return sliced, None if new_sel is None else new_sel[:k]


# -- group-by: dense-domain path --------------------------------------------

def _int32_holds(km: _KeyMeta) -> bool:
    """True when the key's (lo, hi) domain bounds both fit in int32, i.e.
    slot math can run in widened int32 exactly (the common case)."""
    return -(1 << 31) <= km.lo and km.hi < (1 << 31)


def _dense_slot(col: Column, km: _KeyMeta) -> tuple[jax.Array, jax.Array]:
    """(slot, in-domain mask).  Rows whose key value falls outside the
    static (lo, hi) domain — only possible with a user-supplied hint that
    under-covers, since probed/dictionary domains are exact — are masked
    out rather than allowed to alias into neighboring cells."""
    raw = col.data
    ok = (raw >= jnp.asarray(km.lo, raw.dtype)) & \
         (raw <= jnp.asarray(km.hi, raw.dtype))
    if _int32_holds(km):
        # lo/hi fit in int32: widen first so narrow keys (int8 spanning
        # -128..127 has a 256-wide residual that int8 cannot hold) never
        # wrap during the subtraction.
        v = raw.astype(jnp.int32) - jnp.int32(km.lo)
    else:
        # lo/hi exceed the int32 range (int64/uint timestamps clustered
        # around 2**40): subtract in the key's native dtype — the
        # *residual* always fits in int32 (span <= _dense_max_cells).
        # Out-of-domain rows may wrap here; ``ok`` masks them below.
        v = (raw - jnp.asarray(km.lo, raw.dtype)).astype(jnp.int32)
    if km.nullable:
        v = v + 1
        if col.validity is not None:
            v = jnp.where(col.validity, v, 0)
            ok = ok | ~col.validity        # null rows use the null slot
    return v, ok


#: Rows per dense-aggregation scan chunk.  The aggregation runs as ONE
#: lax.scan pass with (cells,)-shaped accumulator carries: the scan body is
#: a small XLA graph compiled once (a flat (cells, rows) broadcast
#: formulation measured 234s-to-timeout XLA *compile* times at ~136 cells
#: on v5e; runtime was never the problem), and the (cells, chunk)
#: broadcasts live in VMEM instead of HBM.
DENSE_CHUNK_ROWS = 131072


def _psum_gather(v: jax.Array, axis: str, axis_size: int) -> jax.Array:
    """all_gather expressed as one psum: shard i contributes row i of a
    zero (P, ...) buffer.  The target TPU compile stack lowers only SUM
    all-reduces (pmin/pmax/all_gather fail AOT lowering), so every
    cross-shard merge must reduce to psum; the buffers here are
    (shards, cells)-sized — bytes, not rows."""
    idx = jax.lax.axis_index(axis)
    buf = jnp.zeros((axis_size,) + v.shape, v.dtype).at[idx].set(v)
    return jax.lax.psum(buf, axis)


def _dense_accumulate(cols, sel, step: GroupAggStep, meta: _GroupMeta):
    """One scan pass over the rows → the dense ``(cells,)``-shaped
    accumulator dict for ``meta``'s cell layout.

    Shared by :func:`_trace_group_dense` (which turns the accumulators
    into output columns in the same trace) and the streaming executor's
    partial-aggregate programs (exec/stream.py), which keep the
    accumulators on device across batches and merge them with
    :func:`stream_combine` — every accumulator here is combinable
    cell-wise (sums add, extrema take min/max) EXCEPT firstpos/lastpos,
    whose row positions are batch-local; streaming combine excludes
    first/last for exactly that reason."""
    n = next(iter(cols.values())).size
    G = meta.cells
    strides = []
    s = 1
    for size in reversed(meta.sizes):
        strides.append(s)
        s *= size
    strides = list(reversed(strides))        # key-major lexicographic

    gid = jnp.zeros(n, jnp.int32)
    in_domain = jnp.ones(n, jnp.bool_)
    for km, stride in zip(meta.keys, strides):
        slot, ok = _dense_slot(cols[km.name], km)
        gid = gid + slot * jnp.int32(stride)
        in_domain = in_domain & ok
    live = in_domain if sel is None else (sel & in_domain)
    gid = jnp.where(live, gid, jnp.int32(G))      # dead rows match no cell

    # Which accumulators does each distinct value column need?
    #   count (valid rows), sum, sumsq, min, max, firstpos, lastpos
    needs: dict[str, set] = {}
    for value_name, how, _ in step.aggs:
        need = needs.setdefault(value_name, set())
        if how == "count":
            need.add("count")
        elif how == "sum":
            need.update(("sum", "count"))
        elif how == "mean":
            need.update(("sum", "count"))
        elif how in ("var", "std"):
            need.update(("sum", "sumsq", "count"))
        elif how == "min":
            need.update(("min", "count"))
        elif how == "max":
            need.update(("max", "count"))
        elif how == "first":
            need.add("firstpos")
        elif how == "last":
            need.add("lastpos")

    # Pad to a chunk multiple; padded rows get gid=G (match nothing).
    # The chunk width snaps to the shape-bucket schedule rather than the
    # exact row count: an exact-shape bind of n rows and a bucket-padded
    # bind of the same rows then reduce over IDENTICAL arrays (live
    # values in the same slots, exact zeros in the same pad slots), so
    # float sums/means associate identically and bucketed execution is
    # bit-for-bit equal to exact-shape (for n <= DENSE_CHUNK_ROWS; above
    # that, chunk boundaries shift with length as before).
    from .bucketing import bucket_capacity
    B = min(DENSE_CHUNK_ROWS, bucket_capacity(max(n, 1)))
    n_pad = -n % B
    npad = n + n_pad

    def padded(arr, fill):
        if n_pad == 0:
            return arr
        return jnp.concatenate(
            [arr, jnp.full(n_pad, fill, arr.dtype)])

    gid_p = padded(gid, jnp.int32(G)).reshape(-1, B)
    iota_p = padded(jnp.arange(n, dtype=jnp.int32),
                    jnp.int32(0)).reshape(-1, B)
    xs: dict[str, jax.Array] = {"gid": gid_p, "iota": iota_p}
    init: dict[str, jax.Array] = {"count_all": jnp.zeros(G, jnp.int64)}
    for vn, need in needs.items():
        c = cols[vn]
        key = vn
        xs["v:" + key] = padded(c.data, jnp.zeros((), c.data.dtype)
                                ).reshape(-1, B)
        if c.validity is not None:
            xs["m:" + key] = padded(c.validity, False).reshape(-1, B)
        if "count" in need:
            init["count:" + key] = jnp.zeros(G, jnp.int64)
        if "sum" in need:
            init["sum:" + key] = jnp.zeros(G, _sum_dtype(c.dtype).jnp_dtype)
        if "sumsq" in need:
            init["sumsq:" + key] = jnp.zeros(G, jnp.float64)
        if "min" in need:
            init["min:" + key] = jnp.full(
                G, _minmax_identity(c.dtype, True), c.data.dtype)
        if "max" in need:
            init["max:" + key] = jnp.full(
                G, _minmax_identity(c.dtype, False), c.data.dtype)
        if "firstpos" in need:
            init["firstpos:" + key] = jnp.full(G, npad, jnp.int32)
        if "lastpos" in need:
            init["lastpos:" + key] = jnp.full(G, -1, jnp.int32)

    cell_ids = jnp.arange(G, dtype=jnp.int32)

    def body(acc, chunk):
        oh = chunk["gid"][None, :] == cell_ids[:, None]       # (G, B)
        out = dict(acc)
        out["count_all"] = acc["count_all"] + jnp.sum(
            oh, axis=1, dtype=jnp.int64)
        for vn, need in needs.items():
            c = cols[vn]
            v = chunk["v:" + vn]
            m = oh if c.validity is None else (oh & chunk["m:" + vn][None, :])
            if "count" in need:
                out["count:" + vn] = acc["count:" + vn] + jnp.sum(
                    m, axis=1, dtype=jnp.int64)
            if "sum" in need:
                acc_dt = acc["sum:" + vn].dtype
                out["sum:" + vn] = acc["sum:" + vn] + jnp.where(
                    m, v[None, :], jnp.zeros((), v.dtype)
                ).astype(acc_dt).sum(axis=1)
            if "sumsq" in need:
                fv = v.astype(jnp.float64)
                out["sumsq:" + vn] = acc["sumsq:" + vn] + jnp.where(
                    m, (fv * fv)[None, :], 0.0).sum(axis=1)
            if "min" in need:
                out["min:" + vn] = jnp.minimum(
                    acc["min:" + vn],
                    jnp.where(m, v[None, :],
                              _minmax_identity(c.dtype, True)).min(axis=1))
            if "max" in need:
                out["max:" + vn] = jnp.maximum(
                    acc["max:" + vn],
                    jnp.where(m, v[None, :],
                              _minmax_identity(c.dtype, False)).max(axis=1))
            if "firstpos" in need:
                pos = jnp.where(oh, chunk["iota"][None, :], jnp.int32(npad))
                out["firstpos:" + vn] = jnp.minimum(
                    acc["firstpos:" + vn], pos.min(axis=1))
            if "lastpos" in need:
                pos = jnp.where(oh, chunk["iota"][None, :], jnp.int32(-1))
                out["lastpos:" + vn] = jnp.maximum(
                    acc["lastpos:" + vn], pos.max(axis=1))
        return out, None

    from ..kernels import registry as _kernels
    if _kernels.enabled("groupby"):
        from ..kernels.groupby import dense_accumulate as _pallas_accumulate
        # Trace-time dispatch: the Pallas fold is staged into the jitted
        # whole-plan program (the program cache keys on SRT_KERNELS, so
        # flipping the knob never serves a stale program).  A kernel
        # trace failure falls back to tracing the oracle scan.
        return _kernels.dispatch(
            "groupby",
            lambda: _pallas_accumulate(
                xs, init, body, interpret=_kernels.interpret_mode()),
            lambda: jax.lax.scan(body, init, xs)[0])
    acc, _ = jax.lax.scan(body, init, xs)
    return acc


def _trace_group_dense(cols, sel, step: GroupAggStep, meta: _GroupMeta,
                       axis: Optional[str] = None,
                       axis_size: int = 1):
    """Dense-cell aggregation; with ``axis`` the accumulators are merged
    across mesh shards by psum-based collectives — the whole distributed
    group-by is (cells,)-sized traffic, no shuffle."""
    n = next(iter(cols.values())).size
    acc = _dense_accumulate(cols, sel, step, meta)
    if axis is not None:
        merged = {}
        for k, v in acc.items():
            if k.startswith("min:"):
                merged[k] = _psum_gather(v, axis, axis_size).min(axis=0)
            elif k.startswith("max:"):
                merged[k] = _psum_gather(v, axis, axis_size).max(axis=0)
            elif k.startswith("firstpos:") or k.startswith("lastpos:"):
                raise TypeError(
                    "first/last aggregations are not defined across shards "
                    "(row positions are shard-local); aggregate locally or "
                    "drop them from the distributed plan")
            else:                       # count_all / count / sum / sumsq
                merged[k] = jax.lax.psum(v, axis)
        acc = merged

    if step.sets is None:
        return _dense_level_outputs(cols, step, meta, acc,
                                    tuple(range(len(meta.keys))), n)

    # Grouping sets: the finest level's accumulators reduce along the
    # rolled-up key axes (sum for counts/sums, min/max for extrema) — all
    # levels come from ONE pass over the rows.
    outs, sels = [], []
    for active in step.sets:
        acc_s = _reduce_acc_axes(acc, meta, active)
        o, s = _dense_level_outputs(cols, step, meta, acc_s, active, n)
        outs.append(o)
        sels.append(s)
    out: dict[str, Column] = {}
    for nm in outs[0]:
        pieces = [o[nm] for o in outs]
        validity = None
        if any(p.validity is not None for p in pieces):
            validity = jnp.concatenate([p.valid_mask() for p in pieces])
        out[nm] = Column(data=jnp.concatenate([p.data for p in pieces]),
                         validity=validity, dtype=pieces[0].dtype)
    return out, jnp.concatenate(sels)


def _reduce_acc_axes(acc, meta: _GroupMeta, active: tuple[int, ...]):
    """Reduce finest-level dense accumulators over the inactive key axes.
    Sum-like accumulators add across merged cells; min/max/firstpos/
    lastpos take the corresponding extremum."""
    inactive = tuple(i for i in range(len(meta.keys)) if i not in active)
    if not inactive:
        return acc
    out = {}
    for k, v in acc.items():
        grid = v.reshape(meta.sizes)
        if k.startswith("min:") or k.startswith("firstpos:"):
            red = grid.min(axis=inactive)
        elif k.startswith("max:") or k.startswith("lastpos:"):
            red = grid.max(axis=inactive)
        else:                           # count_all / count / sum / sumsq
            red = grid.sum(axis=inactive)
        out[k] = red.reshape(-1)
    return out


def _dense_level_outputs(cols, step: GroupAggStep, meta: _GroupMeta, acc,
                         active: tuple[int, ...], n: int):
    """Key columns + aggregate outputs for one grouping level, given that
    level's (possibly axis-reduced) accumulators.  ``active`` lists the
    key indices present at this level; inactive keys come back null and
    the grouping-id column counts them."""
    sizes = tuple(meta.sizes[i] for i in active)
    G = 1
    for s in sizes:
        G *= s
    strides = []
    s = 1
    for size in reversed(sizes):
        strides.append(s)
        s *= size
    strides = list(reversed(strides))

    counts_all = acc["count_all"]
    out: dict[str, Column] = {}
    cell = jnp.arange(G, dtype=jnp.int32)
    pos = {ki: j for j, ki in enumerate(active)}
    for i, km in enumerate(meta.keys):
        key_dtype = cols[km.name].dtype
        if i not in pos:
            out[km.name] = Column(
                data=jnp.zeros(G, key_dtype.jnp_dtype),
                validity=jnp.zeros(G, jnp.bool_), dtype=key_dtype)
            continue
        j = pos[i]
        slot = (cell // jnp.int32(strides[j])) % jnp.int32(sizes[j])
        # Reconstruction mirrors _dense_slot: int32 math when lo/hi fit
        # (narrow dtypes' residuals would wrap natively), otherwise the
        # key's native dtype (lo itself exceeds int32).  The null slot's
        # wrapped value (slot-1 == -1 cast unsigned) sits under
        # validity=False and is never observed.
        adj = (slot - 1) if km.nullable else slot
        if _int32_holds(km):
            data = jnp.int32(km.lo) + adj
        else:
            data = (jnp.asarray(km.lo, key_dtype.jnp_dtype)
                    + adj.astype(key_dtype.jnp_dtype))
        validity = (slot > 0) if km.nullable else None
        out[km.name] = Column(data=data.astype(key_dtype.jnp_dtype),
                              validity=validity, dtype=key_dtype)

    for value_name, how, out_name in step.aggs:
        c = cols[value_name]
        dtype = c.dtype
        out_dtype = _agg_out_dtype(dtype, how)
        has_valid = None
        if how == "count_all":
            data = counts_all
        elif how == "count":
            data = acc["count:" + value_name]
        elif how in ("first", "last"):
            idx = (acc["firstpos:" + value_name] if how == "first"
                   else acc["lastpos:" + value_name])
            idx = jnp.clip(idx, 0, n - 1)
            data = jnp.take(c.data, idx)
            has_valid = (jnp.take(c.validity, idx) if c.validity is not None
                         else None)
        elif how == "sum":
            data = acc["sum:" + value_name]
            has_valid = acc["count:" + value_name] > 0
        elif how in ("mean", "var", "std"):
            scale_factor = 10.0 ** dtype.scale if dtype.is_decimal else 1.0
            fsums = acc["sum:" + value_name].astype(jnp.float64) * scale_factor
            fcounts = acc["count:" + value_name].astype(jnp.float64)
            if how == "mean":
                data = fsums / jnp.maximum(fcounts, 1.0)
                has_valid = acc["count:" + value_name] > 0
            else:
                sumsq = acc["sumsq:" + value_name] * (scale_factor
                                                      * scale_factor)
                denom = jnp.maximum(fcounts - 1.0, 1.0)
                var = (sumsq - fsums * fsums
                       / jnp.maximum(fcounts, 1.0)) / denom
                var = jnp.maximum(var, 0.0)
                data = var if how == "var" else jnp.sqrt(var)
                has_valid = acc["count:" + value_name] > 1
        else:                                 # min / max
            data = acc[how + ":" + value_name]
            has_valid = acc["count:" + value_name] > 0
        out[out_name] = Column(data=data.astype(out_dtype.jnp_dtype),
                               validity=has_valid, dtype=out_dtype)

    if step.sets is not None:
        out[step.grouping_id] = Column(
            data=jnp.full(G, len(meta.keys) - len(active), jnp.int64),
            dtype=INT64)
    return out, counts_all > 0


# -- group-by: sorted fallback path ------------------------------------------

def _trace_group_sorted(cols, sel, step: GroupAggStep, meta: _GroupMeta):
    from .sorted_group import sorted_group_agg
    if step.sets is None:
        return sorted_group_agg(cols, sel, step)
    return _trace_group_sets_sorted(cols, sel, step)


def _trace_group_sets_sorted(cols, sel, step: GroupAggStep):
    """Grouping sets on the sorted path: one segmented pass per level
    (each a multi-operand sort over the key subset), outputs stacked with
    null inactive keys and the grouping-id column.  Levels stay padded at
    the input length; a grand-total level groups by a constant key."""
    from .sorted_group import sorted_group_agg
    n = next(iter(cols.values())).size
    outs, sels = [], []
    for active in step.sets:
        sub_keys = tuple(step.keys[i] for i in active)
        level_cols = cols
        if not sub_keys:                 # grand total: constant key
            level_cols = dict(cols)
            level_cols["__gs_total__"] = Column(
                data=jnp.zeros(n, jnp.int32), dtype=INT32)
            sub_keys = ("__gs_total__",)
        sub = GroupAggStep(sub_keys, step.aggs,
                           tuple(None for _ in sub_keys))
        o, s = sorted_group_agg(level_cols, sel, sub)
        o.pop("__gs_total__", None)
        for i, km_name in enumerate(step.keys):
            if i not in active:
                src = cols[km_name]
                o[km_name] = Column(
                    data=jnp.zeros(n, src.data.dtype),
                    validity=jnp.zeros(n, jnp.bool_), dtype=src.dtype)
        o[step.grouping_id] = Column(
            data=jnp.full(n, len(step.keys) - len(active), jnp.int64),
            dtype=INT64)
        outs.append(o)
        sels.append(s if s is not None else jnp.ones(n, jnp.bool_))
    out: dict[str, Column] = {}
    for nm in outs[0]:
        pieces = [o[nm] for o in outs]
        validity = None
        if any(p.validity is not None for p in pieces):
            validity = jnp.concatenate([p.valid_mask() for p in pieces])
        out[nm] = Column(data=jnp.concatenate([p.data for p in pieces]),
                         validity=validity, dtype=pieces[0].dtype)
    return out, jnp.concatenate(sels)


# -- UNION ALL ---------------------------------------------------------------

def _trace_union(cols, sel, side, meta: _UnionMeta):
    """Run the branch's program inline and concatenate its padded rows
    with the current state (one fused program; no host glue)."""
    prefix = f"__union{meta.index}__:"
    bcols_in = {nm: side[prefix + nm] for nm in meta.exec_names}
    bside = {nm: side[prefix + "side:" + nm] for nm in meta.side_names}
    prog = _assemble(meta.steps, meta.group_metas, meta.join_metas,
                     union_metas=meta.union_metas, jit=False)
    bcols, bsel = prog(bcols_in, bside)

    mine = {nm for nm in cols if not _is_engine_hidden(nm)}
    theirs = {nm for nm in bcols if not _is_engine_hidden(nm)}
    if mine != theirs:
        raise TypeError(f"union_all schema mismatch at trace time: "
                        f"{sorted(mine)} vs {sorted(theirs)}")
    n1 = next(iter(cols.values())).size
    n2 = next(iter(bcols.values())).size
    out: dict[str, Column] = {}
    for nm in mine:
        a, b = cols[nm], bcols[nm]
        if a.dtype != b.dtype:
            raise TypeError(
                f"union_all dtype mismatch for {nm!r}: {a.dtype} vs "
                f"{b.dtype}; cast one side first")
        validity = None
        if a.validity is not None or b.validity is not None:
            validity = jnp.concatenate([a.valid_mask(), b.valid_mask()])
        out[nm] = Column(data=jnp.concatenate([a.data, b.data]),
                         validity=validity, dtype=a.dtype)
    new_sel = None
    if sel is not None or bsel is not None:
        s1 = jnp.ones(n1, jnp.bool_) if sel is None else sel
        s2 = jnp.ones(n2, jnp.bool_) if bsel is None else bsel
        new_sel = jnp.concatenate([s1, s2])
    return out, new_sel


# ---------------------------------------------------------------------------
# program assembly + cache
# ---------------------------------------------------------------------------

#: signature -> assembled program, LRU-ordered (most recent last).  Bounded
#: by config.compile_cache_cap(): a long session over churning schemas
#: must not grow the program table without bound.  Eviction drops the
#: python closure; the XLA executable stays reusable via the persistent
#: compile cache (config.ensure_compile_cache), so an evicted signature
#: re-traces but does not re-compile.
_COMPILED: "OrderedDict" = OrderedDict()

#: ONE lock for every program LRU routed through :func:`_lru_lookup`
#: (``_COMPILED``, ``exec.dist._DIST_COMPILED``, ``parallel.mesh.
#: _DIST_PROGRAMS``) plus the wholesale clears in ``resilience.recovery.
#: evict_device_caches``.  Reentrant because ``build()`` may itself bind
#: a nested plan (split rung, shuffled-join lowering) and land back in a
#: lookup on the same thread.  Held across the whole get-or-insert so
#: concurrent serving threads never double-compile one signature or race
#: the LRU's move-to-end/eviction bookkeeping.
_CACHE_LOCK = threading.RLock()

#: query_id -> {"hit": n, "miss": n} — per-query compile-cache
#: attribution for the serving layer (which queries share programs, which
#: pay the compiles).  Mutated only under ``_CACHE_LOCK``; bounded by
#: dropping oldest entries past _CACHE_ATTRIB_KEEP.
_CACHE_ATTRIBUTION: "OrderedDict" = OrderedDict()
_CACHE_ATTRIB_KEEP = 256


def _attribute_lookup(hit: bool) -> None:
    """Charge a cache hit/miss to the current live query (if any).
    Caller holds ``_CACHE_LOCK``."""
    from ..obs.live import current
    lq = current()
    qid = getattr(lq, "query_id", None)
    if not qid:
        return
    rec = _CACHE_ATTRIBUTION.get(qid)
    if rec is None:
        rec = _CACHE_ATTRIBUTION[qid] = {"hit": 0, "miss": 0}
        while len(_CACHE_ATTRIBUTION) > _CACHE_ATTRIB_KEEP:
            _CACHE_ATTRIBUTION.popitem(last=False)
    rec["hit" if hit else "miss"] += 1


def cache_attribution(query_id=None):
    """Per-query compile-cache hit/miss counts (copies, race-free).
    With ``query_id`` returns that query's ``{"hit": n, "miss": n}`` (or
    None); without, a dict of all retained queries."""
    with _CACHE_LOCK:
        if query_id is not None:
            rec = _CACHE_ATTRIBUTION.get(query_id)
            return dict(rec) if rec is not None else None
        return {q: dict(rec) for q, rec in _CACHE_ATTRIBUTION.items()}

#: dictionary tuple -> device strings column of the uniques, so repeat
#: materializations of a string-keyed plan skip the host rebuild +
#: host-to-device transfer.
_DECODED_DICTS: dict = {}


def _step_closures(steps: tuple, group_metas: tuple[_GroupMeta, ...],
                   join_metas: tuple, axis: Optional[str] = None,
                   axis_size: int = 1, union_metas: tuple = ()):
    """Per-step trace callables ``fn(cols, sel, side) -> (cols, sel)`` —
    THE single step-dispatch table, shared by :func:`_assemble` (which
    chains them into one fused program) and :func:`analyze_plan` (which
    jits each one separately for per-step measurement).  Static plan-shape
    validation (the sharded-state rules) happens here, at build time."""
    from .join import ShuffledJoinMeta, trace_join, trace_join_shuffled
    fns = []
    gi = ji = ui = 0
    sharded = axis is not None
    for step in steps:
        if isinstance(step, FilterStep):
            fns.append(lambda cols, sel, side, step=step:
                       _trace_filter(cols, sel, step))
        elif isinstance(step, ProjectStep):
            fns.append(lambda cols, sel, side, step=step:
                       _trace_project(cols, sel, step))
        elif isinstance(step, GroupAggStep):
            meta = group_metas[gi]
            gi += 1
            if not meta.dense:
                if sharded:
                    raise TypeError(
                        "distributed plans need a dense-domain group-by "
                        "(small static key domains); use "
                        "parallel.dist_groupby for the shuffle-based "
                        "general case")
                fns.append(lambda cols, sel, side, step=step, meta=meta:
                           _trace_group_sorted(cols, sel, step, meta))
            else:
                g_axis = axis if sharded else None
                fns.append(lambda cols, sel, side, step=step, meta=meta,
                           g_axis=g_axis:
                           _trace_group_dense(cols, sel, step, meta,
                                              axis=g_axis,
                                              axis_size=axis_size))
            sharded = False
        elif step is _JOIN_MARKER:
            meta = join_metas[ji]
            ji += 1
            if isinstance(meta, ShuffledJoinMeta):
                if sharded:
                    raise TypeError(
                        "shuffled join inside a sharded program — "
                        "run_plan_dist lowers it through the mesh "
                        "shuffle before assembly (internal error)")
                fns.append(lambda cols, sel, side, meta=meta:
                           trace_join_shuffled(cols, sel, side, meta))
            else:
                fns.append(lambda cols, sel, side, meta=meta:
                           trace_join(cols, sel, side, meta))
        elif step is _UNION_MARKER:
            if sharded:
                raise TypeError(
                    "union_all of still-sharded rows is not supported "
                    "in a distributed plan; aggregate first")
            meta = union_metas[ui]
            ui += 1
            fns.append(lambda cols, sel, side, meta=meta:
                       _trace_union(cols, sel, side, meta))
        elif isinstance(step, WindowStep):
            if sharded:
                raise TypeError(
                    "window functions over still-sharded rows are not "
                    "supported in a distributed plan (partitions span "
                    "shards); aggregate first or window locally")
            from .window import trace_window
            fns.append(lambda cols, sel, side, step=step:
                       trace_window(cols, sel, step))
        elif isinstance(step, SortStep):
            if sharded:
                raise TypeError(
                    "global sort of still-sharded rows is not supported "
                    "in a distributed plan; aggregate first")
            fns.append(lambda cols, sel, side, step=step:
                       _trace_sort(cols, sel, step))
        elif isinstance(step, LimitStep):
            if sharded:
                raise TypeError(
                    "limit over still-sharded rows is not supported in "
                    "a distributed plan; aggregate first")
            fns.append(lambda cols, sel, side, step=step:
                       _trace_limit(cols, sel, step))
        elif isinstance(step, TopKStep):
            if sharded:
                raise TypeError(
                    "top-k over still-sharded rows is not supported in "
                    "a distributed plan; aggregate first")
            fns.append(lambda cols, sel, side, step=step:
                       _trace_topk(cols, sel, step))
        else:
            raise TypeError(f"unknown plan step {step!r}")
    return fns


def _assemble(steps: tuple, group_metas: tuple[_GroupMeta, ...],
              join_metas: tuple, axis: Optional[str] = None,
              axis_size: int = 1, union_metas: tuple = (),
              jit: bool = True):
    """Build the traced function for a plan (independent of concrete data).

    With ``axis`` the program runs per-shard under ``shard_map`` over
    row-sharded inputs: the first (dense) group-by merges its accumulators
    with mesh collectives, after which state is replicated and every later
    step runs identically on all shards.  Steps that would need a global
    view of still-sharded rows raise at assembly time.
    """
    fns = _step_closures(steps, group_metas, join_metas, axis=axis,
                         axis_size=axis_size, union_metas=union_metas)

    def program(cols: dict[str, Column], side: dict[str, Column],
                init_sel=None):
        sel = init_sel
        for fn in fns:
            cols, sel = fn(cols, sel, side)
        return cols, sel

    if axis is not None or not jit:
        return program
    return jax.jit(program)


def _lru_lookup(cache, key, build, prefix, instant_name=None, **instant_kw):
    """Generic bounded-LRU lookup with hit/miss/size/eviction accounting.

    ``cache`` is an ``OrderedDict`` shared with :func:`evict_device_caches`
    (resilience/recovery.py clears it wholesale on OOM); ``build()`` runs
    on a miss; every cache shares ONE cap (``SRT_COMPILE_CACHE_CAP``).
    ``prefix`` names the metric family (``plan.compile_cache``,
    ``dist.compile_cache``, ``dist.programs``); ``instant_name`` keeps
    the plan cache's historical timeline names while new caches default
    to ``<prefix>.hit/miss``.  Returns ``(program, was_hit)``.

    Thread-safe: the whole get-or-insert runs under ``_CACHE_LOCK`` so
    concurrent queries sharing one signature compile it exactly once and
    eviction counts stay exact (the serving layer runs many queries over
    these caches at once).  The miss-path ``build()`` stays inside the
    lock deliberately — atomic get-or-insert is the contract; a second
    thread wanting the same key must wait for (and then reuse) the first
    thread's program rather than tracing its own.
    """
    from ..config import compile_cache_cap, ensure_compile_cache
    from ..obs.metrics import counter, gauge
    from ..obs.timeline import instant, span
    ensure_compile_cache()
    iname = instant_name or prefix
    with _CACHE_LOCK:
        fn = cache.get(key)
        hit = fn is not None
        if fn is None:
            counter(f"{prefix}.miss").inc()
            instant(f"{iname}.miss", cat="compile", **instant_kw)
            with span("compile.build", cat="compile"):
                fn = build()
            cache[key] = fn
            cap = compile_cache_cap()
            while len(cache) > cap:
                cache.popitem(last=False)
                counter(f"{prefix}.evictions").inc()
        else:
            counter(f"{prefix}.hit").inc()
            instant(f"{iname}.hit", cat="compile", **instant_kw)
            cache.move_to_end(key)
        _attribute_lookup(hit)
        gauge(f"{prefix}.size").set(len(cache))
    return fn, hit


def _cache_key(key):
    """The enabled Pallas kernel set joins every program-cache key:
    traced programs bake the kernel-vs-oracle choice in, so an
    ``SRT_KERNELS`` flip must never serve a program traced under the
    other setting."""
    from .. import config
    return (key, config.kernels())


def _cache_lookup(key, build):
    """LRU lookup in the whole-plan program table; ``build()`` runs on a
    miss.  Returns ``(program, was_hit)`` — the streaming executor
    reports the hit flag as its donation-reuse counter."""
    return _lru_lookup(_COMPILED, _cache_key(key), build,
                       "plan.compile_cache",
                       instant_name="compile_cache")


def _compiled_for(bound: _Bound):
    def build():
        return _assemble(bound.assembly_steps(), tuple(bound.group_metas),
                         tuple(bound.join_metas),
                         union_metas=tuple(bound.union_metas))
    return _cache_lookup(bound.signature(), build)[0]


def _program_cost_info(fn, bound: _Bound, deep: bool = False) -> dict:
    """Best-effort XLA cost/memory analysis for one whole-plan program —
    the compile-time half of the cost ledger (obs/profile.py).

    ``fn.lower(...)`` is tracing only (no XLA optimization), so the
    shallow path is cheap enough for the metered run; results are
    memoized per program signature by ``profile.cached_analysis``.
    ``deep=True`` (explain_analyze, where diagnostic cost is accepted)
    additionally AOT-compiles the lowering for ``memory_analysis()`` —
    the hot run path never pays that recompile.  Any failure (older jax,
    backend without cost analysis) degrades to ``available: False``; the
    ledger then reports compute-only attribution.
    """
    from ..utils.memory import _tree_nbytes
    info = {"available": False, "deep": deep, "flops": 0.0,
            "bytes_accessed": 0.0,
            "static_bytes": int(_tree_nbytes((bound.exec_cols,
                                              bound.side_inputs)))}
    try:
        lowered = fn.lower(bound.exec_cols, bound.side_inputs,
                           bound.init_sel)
    except Exception:
        return info
    try:
        ca = lowered.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict) and ca:
        info["available"] = True
        info["flops"] = float(ca.get("flops", 0.0) or 0.0)
        info["bytes_accessed"] = float(ca.get("bytes accessed", 0.0) or 0.0)
    if deep:
        try:
            ma = lowered.compile().memory_analysis()
        except Exception:
            ma = None
        if ma is not None:
            static = sum(int(getattr(ma, attr, 0) or 0) for attr in
                         ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes"))
            if static > 0:
                info["static_bytes"] = static
    return info


# -- streaming-executor entry points (exec/stream.py) ------------------------

def compiled_stream_for(bound: _Bound):
    """The buffer-donating variant of :func:`_compiled_for`.

    Same trace as the plain program (so streamed results are bit-for-bit
    identical to ``run_plan``) but jitted with ``donate_argnums=0``: XLA
    reuses the input columns' device buffers for the outputs, so a stream
    of same-bucket batches cycles one buffer set instead of allocating per
    batch.  The caller must only pass engine-owned buffers (the streaming
    executor donates bucket-padded copies exclusively — never the user's
    table, whose buffers the pad cache and the user still reference).
    Returns ``(program, was_cache_hit)``.
    """
    def build():
        program = _assemble(bound.assembly_steps(),
                            tuple(bound.group_metas),
                            tuple(bound.join_metas),
                            union_metas=tuple(bound.union_metas), jit=False)
        return jax.jit(program, donate_argnums=(0,))
    return _cache_lookup(("stream/donate", bound.signature()), build)


def stream_prefix_dtypes(bound: _Bound) -> dict[str, DType]:
    """Dtypes of the columns reaching the plan's final (group-by) step:
    ``jax.eval_shape`` over the prefix program — Column dtype is static
    pytree aux, so this traces without touching device data.  The
    streaming combine setup uses these to build its batch-invariant cell
    layout and the dtype stubs for :func:`stream_finalize`."""
    fns = _step_closures(bound.assembly_steps()[:-1], (),
                         tuple(bound.join_metas),
                         union_metas=tuple(bound.union_metas))

    def prefix(cols, side, init_sel):
        sel = init_sel
        for fn in fns:
            cols, sel = fn(cols, sel, side)
        return cols

    out = jax.eval_shape(prefix, bound.exec_cols, bound.side_inputs,
                         bound.init_sel)
    return {name: c.dtype for name, c in out.items()}


def compiled_stream_partial(bound: _Bound, smeta: _GroupMeta,
                            donate: bool):
    """Jitted partial-aggregate program for streaming combine mode:
    prefix steps → :func:`_dense_accumulate` under the batch-invariant
    ``smeta`` cell layout, returning the on-device accumulator dict
    instead of output columns (no per-batch materialize, no host sync).
    ``donate`` applies ``donate_argnums=0`` (engine-owned padded inputs
    only, as in :func:`compiled_stream_for`).  The cache key swaps the
    bound's batch-probed group metas for ``smeta`` so every same-bucket
    batch reuses one program.  Returns ``(program, was_cache_hit)``."""
    sig = bound.signature()
    step = bound.steps[-1]
    key = ("stream/partial", donate, sig[0][:-1], sig[1], sig[2], sig[3],
           sig[5], sig[6], sig[7], step, smeta)

    def build():
        fns = _step_closures(sig[0][:-1], (), tuple(bound.join_metas),
                             union_metas=tuple(bound.union_metas))

        def partial_program(cols, side, init_sel=None):
            sel = init_sel
            for fn in fns:
                cols, sel = fn(cols, sel, side)
            return _dense_accumulate(cols, sel, step, smeta)

        return jax.jit(partial_program,
                       donate_argnums=(0,) if donate else ())
    return _cache_lookup(key, build)


_STREAM_COMBINE = None


def stream_combine():
    """The jitted cell-wise accumulator merge for streaming combine mode:
    sums/counts add, extrema take min/max.  Donates the first input —
    outputs match its buffers one-to-one, so each merge runs in place and
    the stream's aggregation state stays one accumulator-set of HBM per
    combine-tree level (the second input's buffers free by refcount as
    the caller drops them).  One jit handles every accumulator pytree
    (jax re-specializes per structure)."""
    global _STREAM_COMBINE
    with _CACHE_LOCK:
        if _STREAM_COMBINE is None:
            def combine(a, b):
                out = {}
                for k, v in a.items():
                    if k.startswith("min:"):
                        out[k] = jnp.minimum(v, b[k])
                    elif k.startswith("max:"):
                        out[k] = jnp.maximum(v, b[k])
                    else:           # count_all / count: / sum: / sumsq:
                        out[k] = v + b[k]
                return out
            _STREAM_COMBINE = jax.jit(combine, donate_argnums=(0,))
        return _STREAM_COMBINE


def stream_merge_cells(acc: dict, axis: str, axis_size: int) -> dict:
    """Cross-shard merge body for the sharded streaming executor's ONE
    end-of-stream collective (exec/dist_stream.py wraps this in
    ``shard_map``).  Each shard enters holding its ``(1, cells)`` block
    of the stacked per-shard accumulators; additive accumulators
    (count/sum/sumsq) merge with a single psum, and extrema ride the
    psum-gather trick — the target TPU stack lowers only SUM all-reduces
    (:func:`_psum_gather`) — then reduce shard-locally.  Output is the
    replicated ``(cells,)`` accumulator dict :func:`stream_finalize`
    materializes, so a whole sharded stream pays collective traffic
    once, not once per batch."""
    out = {}
    for k, v in acc.items():
        v = v[0]                 # this shard's (1, cells) block
        if k.startswith("min:"):
            out[k] = jnp.min(_psum_gather(v, axis, axis_size), axis=0)
        elif k.startswith("max:"):
            out[k] = jnp.max(_psum_gather(v, axis, axis_size), axis=0)
        else:                    # count_all / count: / sum: / sumsq:
            out[k] = jax.lax.psum(v, axis)
    return out


def stream_finalize(bound: _Bound, smeta: _GroupMeta, acc,
                    col_dtypes: dict[str, DType]) -> Table:
    """Output columns + materialization from a combined streaming
    accumulator — the stream's ONE host sync.  ``bound`` is any batch's
    binding (used for output order only).  The dense-cell outputs read
    nothing but dtypes from their input columns except for first/last —
    which streaming combine excludes — so dtype-only stubs suffice."""
    step = bound.steps[-1]
    stubs = {name: Column(data=None, dtype=dt)
             for name, dt in col_dtypes.items()}

    def outputs(acc):
        return _dense_level_outputs(stubs, step, smeta, acc,
                                    tuple(range(len(smeta.keys))), 1)

    out_cols, live = jax.jit(outputs)(acc)
    return materialize(bound, out_cols, live)


_CACHED_SOURCE_RESOLVER = None


def set_cached_source_resolver(fn) -> None:
    """Register the semantic cache's ``key -> Table`` resolver for
    :class:`~.plan.CachedSourceStep` leaves (serve/semantic.py installs
    it once at first use; ``None`` uninstalls).  Kept as a registration
    hook so the executor stays import-independent of the serving
    layer."""
    global _CACHED_SOURCE_RESOLVER
    _CACHED_SOURCE_RESOLVER = fn


def _resolve_cached_source(plan: Plan, table: Table):
    """Resolve a leading ``CachedSourceStep`` into its materialized
    prefix Table and strip the marker — identity for ordinary plans.

    Runs ONCE at the top of :func:`run_plan`, before the empty-input
    check, the recovery ladder, and batch splitting, so every downstream
    path (retry, OOM split, metering) operates on the resolved input and
    can never re-resolve half-split inputs against the full cached
    fragment."""
    if not plan.steps or not isinstance(plan.steps[0], CachedSourceStep):
        return plan, table
    step = plan.steps[0]
    if _CACHED_SOURCE_RESOLVER is None:
        raise RuntimeError(
            f"plan carries CachedSourceStep({step.key!r}) but no cached-"
            f"source resolver is registered (serve/semantic.py installs "
            f"one; a spliced plan cannot run outside it)")
    resolved = _CACHED_SOURCE_RESOLVER(step.key)
    if resolved is None:
        raise RuntimeError(
            f"semantic cache entry {step.key!r} is gone (evicted without "
            f"a pin?) — the spliced plan cannot run")
    # Position-preserving payloads carry (table, names, sel_name): the
    # table is padded at the source's logical length and the prefix's
    # live-row selection rides as a column, so the suffix re-enters the
    # exact (columns, selection) state of the fused program — float
    # accumulation order, and therefore bits, match the oracle.  A bare
    # Table (legacy/diagnostic resolvers) splices compacted.
    from .optimize import resume_prefix_steps
    if isinstance(resolved, tuple):
        resolved, names, sel_name = resolved
        pre = resume_prefix_steps(names, sel_name)
    else:
        pre = ()
    stripped = Plan(pre + tuple(plan.steps[1:]))
    info = getattr(plan, "opt", None)
    if info is not None:
        object.__setattr__(stripped, "opt", info)
    # Non-field marker (like Plan.opt): lets the postmortem bundle's
    # semantic block tell a spliced query from a full recompute.
    object.__setattr__(stripped, "_cached_source_key", step.key)
    from ..obs.metrics import counter
    counter("serve.semantic.resolved").inc()
    return stripped, resolved


def _bind(plan: Plan, table: Table) -> _Bound:
    """Bind through the shape-bucketing layer: pad the input up to its
    bucket capacity (exec/bucketing.py) and carry the live-row mask as
    both the program's initial selection and the stats-probe mask, so
    every row count in a bucket shares one compiled program and pad rows
    never widen key domains.  Exact-shape bind when bucketing is off or
    inapplicable (SRT_SHAPE_BUCKETS=0, shuffled-join plans, nested/
    two-word columns)."""
    from .bucketing import prepare_input
    if plan.steps and isinstance(plan.steps[0], CachedSourceStep):
        raise RuntimeError(
            "CachedSourceStep reached _bind unresolved — spliced plans "
            "must enter through run_plan")
    table = _pruned_input(plan, table)
    bi = prepare_input(plan, table)
    if bi is None:
        return _Bound(plan, table)
    return _Bound(plan, bi.table, probe_mask=bi.live_mask,
                  init_sel=bi.live_mask, logical_rows=bi.logical_rows)


# ---------------------------------------------------------------------------
# execution + materialization
# ---------------------------------------------------------------------------

def _final_order(steps: tuple, initial: tuple[str, ...]) -> tuple[str, ...]:
    """Output column order, derived statically (jit pytrees sort dict keys,
    so insertion order must be reconstructed from the plan)."""
    order = list(initial)
    for step in steps:
        if isinstance(step, ProjectStep):
            if step.narrow:
                order = [nm for nm, _ in step.cols]
            else:
                for nm, _ in step.cols:
                    if nm not in order:
                        order.append(nm)
        elif isinstance(step, GroupAggStep):
            order = list(step.keys) + [out for _, _, out in step.aggs]
            if step.sets is not None:
                order.append(step.grouping_id)
        elif isinstance(step, (JoinStep, JoinShuffledStep)) \
                and step.how in ("inner", "left"):
            order += [nm for nm in step.table.names
                      if nm not in step.right_on and nm not in order]
        elif isinstance(step, WindowStep):
            if step.out not in order:
                order.append(step.out)
    return tuple(order)


def run_plan_padded(plan: Plan, table: Table):
    if table.num_rows == 0:
        return run_plan_eager(plan, table), None
    from .optimize import optimize
    plan = optimize(plan)
    bound = _bind(plan, table)
    fn = _compiled_for(bound)
    out_cols, sel = fn(bound.exec_cols, bound.side_inputs, bound.init_sel)
    t = _rebuild(bound, out_cols)
    sel_col = None if sel is None else Column(data=sel.astype(jnp.uint8),
                                              dtype=BOOL8)
    return t, sel_col


def run_plan(plan: Plan, table: Table, progress=None) -> Table:
    """``progress`` opts this one query into live-telemetry heartbeats
    (obs/live.py) even without ``SRT_METRICS``: ``True`` renders a
    stderr progress line, a callable receives live snapshots at phase
    transitions.  None (default) pays nothing extra."""
    plan, table = _resolve_cached_source(plan, table)
    if table.num_rows == 0:
        return run_plan_eager(plan, table)
    from .optimize import optimize
    plan = optimize(plan)
    from ..config import metrics_enabled
    if metrics_enabled() or progress is not None:
        return _run_plan_metered(plan, table, progress=progress)[0]
    from ..obs import timeline as _tl
    if _tl.enabled():
        # Correlation id for the recorded spans even on the unmetered
        # path (the metered path scopes with its QueryMetrics id).
        from ..obs.query import next_query_id
        with _tl.query_scope(next_query_id()):
            return _execute_resilient(plan, table)
    return _execute_resilient(plan, table)


def _run_plan_metered(plan: Plan, table: Table, progress=None):
    """run_plan with QueryMetrics accounting (``SRT_METRICS=1``): phase
    wall times, compile-cache status, registry counter deltas, and the
    recovery block (retries / splits / cache evictions — resilience/).
    The program invocation is explicitly blocked on
    (jax.block_until_ready) so execute_seconds means device wall, not
    dispatch latency — a measurement barrier the unmetered path does not
    pay, which is why metering is a flag into the shared resilient core
    and not inline ifs at every call site."""
    import time as _time
    from ..obs import live as _live
    from ..obs import timeline as _tl
    from ..obs.history import plan_fingerprint
    from ..obs.metrics import counters_delta, registry
    from ..obs.query import QueryMetrics, next_query_id, \
        set_last_query_metrics
    from ..resilience import recovery_stats
    from ..obs import profile as _prof
    from .optimize import source_plan
    # Fingerprints and history records key on the user's ORIGINAL plan:
    # that is the object the next session's optimize() fingerprints when
    # it looks its history up.
    src = source_plan(plan)
    qm = QueryMetrics(query_id=next_query_id(), mode="run",
                      fingerprint=plan_fingerprint(src),
                      input_rows=table.num_rows,
                      input_columns=table.num_columns)
    lq = _live.start("run", query_id=qm.query_id,
                     fingerprint=qm.fingerprint,
                     input_rows=table.num_rows,
                     observer=_live.as_observer(progress))
    before = registry().counters_snapshot()
    r_before = recovery_stats().snapshot()
    t_all = _time.perf_counter()
    cc = _prof.push_collector()
    try:
        with _tl.query_scope(qm.query_id):
            t = _execute_resilient(plan, table, qm=qm)
    except BaseException as err:
        lq.finish(status="error", error=repr(err))
        from ..obs import bundle as _bundle
        _bundle.dump("failure", qm=qm, error=err, plan=plan)
        raise
    finally:
        _prof.pop_collector(cc)
    qm.total_seconds = _time.perf_counter() - t_all
    qm.output_rows = t.num_rows
    cc.apply(qm)
    qm.finish_counters(counters_delta(before))
    qm.apply_recovery(recovery_stats().delta(r_before))
    lq.note_hbm(qm.hbm_peak_bytes)
    lq.finish(output_rows=t.num_rows)
    qm.apply_opt(getattr(plan, "opt", None))
    set_last_query_metrics(qm)
    from ..obs.history import maybe_record
    maybe_record(src, qm, optimized=plan)
    return t, qm


def _execute_resilient(plan: Plan, table: Table, qm=None,
                       depth: int = 0) -> Table:
    """bind → dispatch → materialize under the HBM-OOM recovery ladder.

    Each phase runs inside ``resilience.recovery.oom_ladder`` (evict the
    program + pad caches, backoff, retry — bounded by ``SRT_RETRY_MAX``);
    when dispatch or materialize stays OOM past the budget the batch is
    split in half along rows (:func:`_split_batch`) and the pieces rerun
    through this same function.  ``qm`` switches on phase metering
    (blocking the invocation so execute_seconds is device wall).  The
    named fault sites (``bind``, ``dispatch``, ``materialize``) let
    ``SRT_FAULT`` provoke every path deterministically on CPU."""
    import time as _time
    from ..obs import live as _live
    from ..obs.timeline import span as _tspan
    from ..resilience import fault_point
    from ..resilience.classify import ExecutionRecoveryError
    from ..resilience.recovery import SplitUnavailable, oom_ladder

    def do_bind():
        fault_point("bind")
        return _bind(plan, table)

    t0 = _time.perf_counter()
    _live.phase("bind")
    with _tspan("run.bind", cat="execute", step_kind="bind",
                rows=table.num_rows, depth=depth):
        bound = oom_ladder("bind", do_bind)
    if qm is not None:
        qm.bind_seconds += _time.perf_counter() - t0
        with _CACHE_LOCK:
            qm.compile_cache = ("hit"
                                if _cache_key(bound.signature())
                                in _COMPILED else "miss")
        qm.steps = _static_step_metrics(bound)

    def do_dispatch():
        fault_point("dispatch")
        fn = _compiled_for(bound)
        out = fn(bound.exec_cols, bound.side_inputs, bound.init_sel)
        if qm is not None:
            out = jax.block_until_ready(out)
        return out

    try:
        t0 = _time.perf_counter()
        _live.phase("dispatch")
        with _tspan("run.dispatch", cat="execute", step_kind="dispatch",
                    depth=depth):
            out_cols, sel = oom_ladder("dispatch", do_dispatch)
        if qm is not None:
            qm.execute_seconds += _time.perf_counter() - t0
            if qm.compile_cache == "miss":
                qm.compile_seconds = qm.execute_seconds
            from ..obs import profile as _prof
            from ..utils.memory import sample_device_hbm
            # Compile-time cost numbers (memoized per signature) + a
            # live HBM sample at the dispatch boundary feed the ledger.
            # Raw cache read, NOT _compiled_for: the dispatch above just
            # populated it, and a counted lookup here would double the
            # hit/miss accounting the cache tests pin.
            sig = bound.signature()

            def _cached_program():
                with _CACHE_LOCK:
                    return _COMPILED.get(_cache_key(sig))
            _prof.cached_analysis(
                ("plan", sig),
                lambda: _program_cost_info(
                    _cached_program() or _compiled_for(bound), bound))
            sample_device_hbm("run.dispatch")
        t0 = _time.perf_counter()
        _live.phase("materialize")
        with _tspan("run.materialize", cat="execute",
                    step_kind="materialize", depth=depth):
            t = oom_ladder("materialize",
                           lambda: materialize(bound, out_cols, sel))
        if qm is not None:
            qm.materialize_seconds += _time.perf_counter() - t0
            from ..utils.memory import sample_device_hbm
            sample_device_hbm("run.materialize")
        return t
    except ExecutionRecoveryError as err:
        # Last rung: split the batch along rows and re-run the pieces.
        if err.category != "oom":
            raise
        try:
            return _split_batch(plan, table, qm, depth)
        except SplitUnavailable as unavailable:
            err.add_step(f"split-unavailable: {unavailable}")
            raise err


def _split_mode(plan: Plan):
    """How a split batch's piece results recombine: ``"concat"`` for
    row-local plans (every step maps rows independently, so outputs
    concatenate), ``"combine"`` for stream-combinable group-by plans
    (pieces partial-aggregate and merge cell-wise), None when splitting
    cannot preserve semantics (sort/limit/window/non-combinable agg)."""
    steps = plan.steps
    if all(isinstance(s, (FilterStep, ProjectStep, JoinStep))
           for s in steps):
        return "concat"
    from .stream import combine_obstacles
    if not combine_obstacles(plan):
        return "combine"
    return None


def _split_batch(plan: Plan, table: Table, qm, depth: int) -> Table:
    """The recovery ladder's split rung: halve ``table`` along rows —
    with the cut snapped to the bucket schedule so both pieces land in
    already-compiled buckets — and re-run the pieces.  Row-local plans
    concatenate piece outputs; stream-combinable group-bys merge piece
    accumulators (bit-identical grouping, one final materialize).  Raises
    ``SplitUnavailable`` when the plan or batch cannot split."""
    from ..resilience import recovery_stats
    from ..resilience.recovery import MAX_SPLIT_DEPTH, SplitUnavailable
    n = table.num_rows
    if depth >= MAX_SPLIT_DEPTH:
        raise SplitUnavailable(
            f"split depth {depth} reached (MAX_SPLIT_DEPTH="
            f"{MAX_SPLIT_DEPTH}); the OOM is not batch-size-driven")
    if n < 2:
        raise SplitUnavailable(f"batch of {n} row(s) cannot split")
    mode = _split_mode(plan)
    if mode is None:
        raise SplitUnavailable(
            "plan is neither row-local nor stream-combinable (sort/"
            "limit/window or a non-combinable aggregation blocks "
            "piecewise re-execution)")
    from .bucketing import bucket_capacity
    cut = min(bucket_capacity((n + 1) // 2), n - 1)
    recovery_stats().add_split()
    from ..obs.metrics import counter
    from ..obs.timeline import instant
    counter("recovery.split_rows").inc(n)
    instant("recovery.split", cat="resilience", rows=n, cut=cut,
            depth=depth, mode=mode)
    pieces = (table.gather(jnp.arange(0, cut, dtype=jnp.int32)),
              table.gather(jnp.arange(cut, n, dtype=jnp.int32)))
    if mode == "concat":
        from ..ops.common import concat_tables
        return concat_tables([_execute_resilient(plan, piece, qm=qm,
                                                 depth=depth + 1)
                              for piece in pieces])
    return _split_combine(plan, pieces, qm, depth)


def _split_combine(plan: Plan, pieces, qm, depth: int) -> Table:
    """Recombine split pieces of a group-by plan through the streaming
    partial-aggregate machinery: each piece folds into a dense
    accumulator under ONE batch-invariant cell layout, accumulators
    merge cell-wise, and a single finalize materializes — the same
    carry-preserving path ``run_plan_stream`` uses, so grouping is
    independent of where the split landed."""
    from ..resilience.recovery import SplitUnavailable, oom_ladder
    from .stream import _combine_setup
    smeta = dtypes = bound0 = total = None
    for piece in pieces:
        bound = oom_ladder("bind", lambda p=piece: _bind(plan, p))
        if smeta is None:
            try:
                smeta, dtypes = _combine_setup(bound)
            except TypeError as exc:
                raise SplitUnavailable(
                    f"no batch-invariant accumulator layout: {exc}"
                ) from exc
            bound0 = bound
        def do_partial(b=bound):
            fn, _ = compiled_stream_partial(b, smeta, donate=False)
            return fn(b.exec_cols, b.side_inputs, b.init_sel)
        acc = oom_ladder("dispatch", do_partial)
        total = acc if total is None else stream_combine()(total, acc)
    return oom_ladder("materialize",
                      lambda: stream_finalize(bound0, smeta, total, dtypes))


def materialize(bound: _Bound, out_cols: dict[str, Column], sel) -> Table:
    """Compact padded program outputs (ONE host sync when ``sel`` is set)
    and rebuild the user-visible table."""
    from ..resilience import fault_point
    fault_point("materialize")
    if sel is None:
        return _rebuild(bound, out_cols)
    import time as _time
    from ..ops.common import pow2_bucket
    from ..utils.memory import record_host_sync
    t0 = _time.perf_counter()
    count = int(jnp.sum(sel))                     # THE host sync
    record_host_sync("materialize.count", 8,
                     seconds=_time.perf_counter() - t0)
    n = next(iter(out_cols.values())).size
    bucket = min(pow2_bucket(count), n)
    from ..ops.filter import _compact_kernel
    names = list(out_cols)
    idx, datas, valids = _compact_kernel(
        sel, tuple(out_cols[nm].data for nm in names),
        tuple(out_cols[nm].validity for nm in names), bucket=bucket)
    sliced = {nm: Column(data=d[:count],
                         validity=None if v is None else v[:count],
                         dtype=out_cols[nm].dtype)
              for nm, d, v in zip(names, datas, valids)}
    return _rebuild(bound, sliced)


def _rebuild(bound: _Bound, out_cols: dict[str, Column]) -> Table:
    """Materialize program outputs: decode dictionary keys, gather deferred
    string payloads by rowid, drop hidden columns, and restore the
    user-visible column order (jit pytrees sort dict keys)."""
    from ..ops.strings import strings_from_pylist
    rowid = out_cols.get(_ROWID)
    result: dict[str, Column] = {}
    for name, c in out_cols.items():
        if (name == _ROWID or name.startswith("__valid__:")
                or name.startswith("__codes__:")):
            continue
        if name in bound.join_string_srcs:
            # Hidden join rowid: gather each build-side string payload at
            # the final (small) size; unmatched rows are null.
            for src, out_name in bound.join_string_srcs[name]:
                idx = jnp.clip(c.data.astype(jnp.int32), 0,
                               max(src.size - 1, 0))
                g = src.gather(idx)
                v = g.valid_mask() if c.validity is None else (
                    g.valid_mask() & c.validity)
                result[out_name] = Column(data=g.data, offsets=g.offsets,
                                          validity=v, dtype=g.dtype)
            continue
        if name in bound.dictionaries:
            uniq = bound.dictionaries[name]
            dict_col = _DECODED_DICTS.get(uniq)
            if dict_col is None:
                dict_col = strings_from_pylist(list(uniq))
                _DECODED_DICTS[uniq] = dict_col
            codes = jnp.clip(c.data.astype(jnp.int32), 0,
                             max(len(uniq) - 1, 0))
            s = dict_col.gather(codes)
            if c.validity is not None:
                s = Column(data=s.data, offsets=s.offsets,
                           validity=c.validity
                           if s.validity is None else (s.validity & c.validity),
                           dtype=s.dtype)
            result[name] = s
        elif name.startswith("__strref__:"):
            _, src_name, out_name = name.split(":", 2)
            src = bound.string_cols[src_name]
            idx = jnp.clip(c.data.astype(jnp.int32), 0, bound.n - 1)
            s = src.gather(idx)
            if c.validity is not None:
                s = Column(data=s.data, offsets=s.offsets,
                           validity=c.validity if s.validity is None
                           else (s.validity & c.validity), dtype=s.dtype)
            result[out_name] = s
        else:
            result[name] = c
    # Deferred whole-column strings (no groupby consumed them): gather by
    # surviving rowids — only those the plan's final schema keeps (a
    # narrowing select drops the rest).
    order = _final_order(bound.plan.steps, bound.input_names)
    if rowid is not None and bound.string_cols:
        idx = rowid.data.astype(jnp.int32)
        for name, src in bound.string_cols.items():
            if name not in result and name in order:
                result[name] = src.gather(idx)
    ordered = [nm for nm in order if nm in result]
    ordered += [nm for nm in result if nm not in ordered]
    return Table([(nm, result[nm]) for nm in ordered])


def _step_descriptions(bound: _Bound) -> list[tuple[str, str]]:
    """``(kind, text)`` per bound step — the single source of the per-step
    explain text, shared by :func:`explain_plan` and the analyzed tree
    (indices line up with :func:`_step_closures` over assembly_steps)."""
    out: list[tuple[str, str]] = []
    gi = ji = 0
    for step in bound.steps:
        if isinstance(step, FilterStep):
            out.append(("Filter",
                        f"Filter[{render(step.pred)}] -> selection mask"))
        elif isinstance(step, ProjectStep):
            kind = "Select" if step.narrow else "Project"
            out.append((kind,
                        f"{kind}[{', '.join(nm for nm, _ in step.cols)}]"))
        elif isinstance(step, GroupAggStep):
            meta = bound.group_metas[gi]
            gi += 1
            sets = ("" if step.sets is None
                    else f" x{len(step.sets)} grouping sets"
                         f" -> {step.grouping_id}")
            if meta.dense:
                doms = ", ".join(
                    f"{km.name}:[{km.lo},{km.hi}]"
                    + ("+null" if km.nullable else "")
                    for km in meta.keys)
                out.append(("GroupBy[dense]",
                            f"GroupBy[dense, {meta.cells} cells{sets}; "
                            f"{doms}] "
                            f"aggs={[h for _, h, _ in step.aggs]}"))
            else:
                out.append(("GroupBy[sorted]",
                            f"GroupBy[sorted: multi-key sort + segmented "
                            f"scans{sets}] keys={list(step.keys)} "
                            f"aggs={[h for _, h, _ in step.aggs]}"))
        elif isinstance(step, JoinStep):
            meta = bound.join_metas[ji]
            ji += 1
            keys = ", ".join(
                f"{km.probe_name}:[{km.lo},{km.hi}]" for km in meta.keys)
            out.append(("BroadcastJoin",
                        f"BroadcastJoin[{meta.how}, probe={meta.mode}, "
                        f"build={meta.dim_rows} rows] on {keys}"))
        elif isinstance(step, JoinShuffledStep):
            meta = bound.join_metas[ji]
            ji += 1
            out.append(("ShuffledJoin",
                        f"ShuffledJoin[{meta.how}, "
                        f"right={meta.right_rows} rows, "
                        f"capacity={meta.capacity}; bind-time factorize "
                        f"probe] on {', '.join(step.left_on)}"))
        elif isinstance(step, UnionAllStep):
            out.append(("UnionAll",
                        f"UnionAll[branch over {step.table.num_rows} rows, "
                        f"{len(step.plan.steps)} branch steps traced "
                        f"inline]"))
        elif isinstance(step, WindowStep):
            out.append(("Window",
                        f"Window[{step.func} -> {step.out}; partition by "
                        f"{', '.join(step.partition_by)}"
                        + (f"; order by {', '.join(step.order_by)}"
                           if step.order_by else "") + "]"))
        elif isinstance(step, SortStep):
            out.append(("Sort", f"Sort[{', '.join(step.by)}]"))
        elif isinstance(step, LimitStep):
            out.append(("Limit", f"Limit[{step.k}]"))
        elif isinstance(step, TopKStep):
            out.append(("TopK",
                        f"TopK[{', '.join(step.by)} k={step.k}; fused "
                        f"sort+limit, static slice]"))
    return out


def _static_step_metrics(bound: _Bound) -> list:
    """Describe-only StepMetrics (rows/timings unmeasured) for the plain
    metered run path, which never breaks the fused program apart."""
    from ..obs.query import StepMetrics
    return [StepMetrics(index=i, kind=kind, describe=text)
            for i, (kind, text) in enumerate(_step_descriptions(bound))]


def explain_plan(plan: Plan, table: Table) -> str:
    """Human-readable bound physical plan (see Plan.explain)."""
    from .optimize import optimize
    plan = optimize(plan)
    bound = _Bound(plan, table)
    lines = [f"Plan over {table.num_rows} rows x "
             f"{table.num_columns} cols"]
    if bound.dictionaries:
        lines.append(f"  strings dictionary-encoded as keys: "
                     f"{sorted(bound.dictionaries)}")
    if bound.string_cols:
        lines.append(f"  strings via rowid indirection: "
                     f"{sorted(bound.string_cols)}")
    for _, text in _step_descriptions(bound):
        lines.append("  " + text)
    lines.append("  Materialize[compact by selection; "
                 + ("1 host sync]" if any(
                     isinstance(s, (FilterStep, GroupAggStep, JoinStep,
                                    JoinShuffledStep))
                     for s in bound.steps) else "0 host syncs]"))
    info = getattr(plan, "opt", None)
    if info is not None and info.rewrites:
        lines.append(info.render_diff())
    return "\n".join(lines)


def analyze_plan(plan: Plan, table: Table):
    """Execute ``plan`` one jitted program per step, measuring per-step
    wall time and live rows in/out — ``explain_analyze``'s engine.

    Deliberately NOT the production execution shape: each step dispatches
    separately and its live-row count is read back (one small host sync
    per step, kept OUT of the ``host.sync`` counters — the instrument
    does not meter itself).  The whole-plan compile cache is still
    consulted first, so the report's ``cache=``/compile/execute fields
    describe the production fused program.  Returns
    ``(materialized Table, QueryMetrics)``.
    """
    from ..obs import live as _live
    from ..obs import timeline as _tl
    from ..obs.history import plan_fingerprint
    from ..obs.query import QueryMetrics, next_query_id, \
        set_last_query_metrics
    from .optimize import optimize, source_plan
    # Analyze keeps reordered conjuncts one-per-step, so each conjunct's
    # observed selectivity lands in the history — the feedback the run
    # modes' reorder rule reads back.
    plan = optimize(plan, mode="analyze")
    src = source_plan(plan)
    qm = QueryMetrics(query_id=next_query_id(), mode="analyze",
                      fingerprint=plan_fingerprint(src),
                      input_rows=table.num_rows,
                      input_columns=table.num_columns)
    lq = _live.start("analyze", query_id=qm.query_id,
                     fingerprint=qm.fingerprint,
                     input_rows=table.num_rows)
    try:
        with _tl.query_scope(qm.query_id):
            t = _analyze_measured(plan, table, qm, lq)
    except BaseException as err:
        lq.finish(status="error", error=repr(err))
        from ..obs import bundle as _bundle
        _bundle.dump("failure", qm=qm, error=err, plan=plan)
        raise
    lq.finish(output_rows=qm.output_rows)
    qm.apply_opt(getattr(plan, "opt", None))
    set_last_query_metrics(qm)
    from ..obs.history import maybe_record
    maybe_record(src, qm, optimized=plan)
    return t, qm


def _analyze_measured(plan: Plan, table: Table, qm, lq) -> Table:
    """The measured body of :func:`analyze_plan` (runs inside its
    timeline query scope; ``lq`` is the live heartbeat record)."""
    import time as _time
    from ..obs.metrics import counters_delta, registry
    from ..obs.query import StepMetrics
    from ..resilience import recovery_stats
    from ..resilience.recovery import oom_ladder
    from ..obs import profile as _prof
    from ..utils.memory import sample_device_hbm
    before = registry().counters_snapshot()
    r_before = recovery_stats().snapshot()
    cc = _prof.push_collector()
    t_all = _time.perf_counter()
    lq.set_phase("bind")
    bound = _bind(plan, table)
    qm.bind_seconds = _time.perf_counter() - t_all
    qm.compile_cache = ("hit" if _cache_key(bound.signature())
                        in _COMPILED else "miss")
    fn = _compiled_for(bound)
    t0 = _time.perf_counter()
    # The whole-plan dispatch and the final materialize run under the
    # OOM recovery ladder (evict → backoff → retry), so a faulted/
    # recovered explain_analyze still renders — with its recovery block —
    # instead of aborting the report.  (No split rung here: the analyzer
    # measures THE batch it was given; halving it would measure a
    # different query.)
    lq.set_phase("dispatch")
    out_cols, sel = oom_ladder("dispatch", lambda: jax.block_until_ready(
        fn(bound.exec_cols, bound.side_inputs, bound.init_sel)))
    qm.execute_seconds = _time.perf_counter() - t0
    if qm.compile_cache == "miss":
        qm.compile_seconds = qm.execute_seconds
    # deep=True: explain_analyze accepts the AOT recompile that XLA
    # memory_analysis() costs; the memo upgrade benefits later runs too.
    _prof.cached_analysis(("plan", bound.signature()),
                          lambda: _program_cost_info(fn, bound, deep=True),
                          deep=True)
    sample_device_hbm("analyze.dispatch")
    # Per-step measured pass: fresh single-step jits over the same bound
    # inputs.  Diagnostic cost (re-traces every call) is acceptable —
    # explain_analyze is a debugging surface, not a hot path.
    fns = _step_closures(bound.assembly_steps(), tuple(bound.group_metas),
                         tuple(bound.join_metas),
                         union_metas=tuple(bound.union_metas))
    descs = _step_descriptions(bound)
    # Bucketed binds start from the bind-time live mask; rows in/out stay
    # LIVE counts, so the report reads the same at any bucket capacity.
    cols, step_sel = bound.exec_cols, bound.init_sel
    live_in = bound.logical_rows
    lq.set_phase("measure-steps")
    for i, (step_fn, (kind, text)) in enumerate(zip(fns, descs)):
        t0 = _time.perf_counter()
        cols, step_sel = jax.block_until_ready(
            jax.jit(step_fn)(cols, step_sel, bound.side_inputs))
        dt = _time.perf_counter() - t0
        padded = int(next(iter(cols.values())).data.shape[0])
        live = (padded if step_sel is None
                else int(jnp.sum(step_sel)))      # analyzer-only sync
        qm.steps.append(StepMetrics(
            index=i, kind=kind, describe=text, rows_in=live_in,
            rows_out=live, padded_out=padded, seconds=dt,
            density=(live / padded) if padded else 0.0))
        live_in = live
        lq.batch_out(live)
    t0 = _time.perf_counter()
    lq.set_phase("materialize")
    t = oom_ladder("materialize",
                   lambda: materialize(bound, out_cols, sel))
    qm.materialize_seconds = _time.perf_counter() - t0
    sample_device_hbm("analyze.materialize")
    qm.total_seconds = _time.perf_counter() - t_all
    qm.output_rows = t.num_rows
    _prof.pop_collector(cc)
    cc.apply(qm)
    qm.finish_counters(counters_delta(before))
    qm.apply_recovery(recovery_stats().delta(r_before))
    lq.note_hbm(qm.hbm_peak_bytes)
    return t


def explain_analyze_plan(plan: Plan, table: Table,
                         timeline: bool = False) -> str:
    """The analyzed tree behind ``Plan.explain_analyze``.

    With ``SRT_METRICS=1`` runs :func:`analyze_plan` and renders measured
    per-step rows/timings; otherwise renders the same tree with metrics
    marked unavailable (still binds the plan, so the step text is real).
    ``timeline=True`` records the run on the span timeline (regardless of
    ``SRT_TRACE_TIMELINE``) and appends the lane summary to the report.
    """
    if timeline:
        from ..obs.timeline import recording
        with recording() as rec:
            text = explain_analyze_plan(plan, table)
        return text + "\n" + rec.summary()
    from .optimize import optimize
    plan = optimize(plan, mode="analyze")
    from ..config import metrics_enabled
    from ..obs.query import UNMEASURED_FLOAT, QueryMetrics
    header = (f"Plan over {table.num_rows} rows x "
              f"{table.num_columns} cols")
    if not metrics_enabled() or table.num_rows == 0:
        qm = QueryMetrics(mode="analyze", input_rows=table.num_rows,
                          input_columns=table.num_columns,
                          bind_seconds=UNMEASURED_FLOAT,
                          compile_seconds=UNMEASURED_FLOAT,
                          execute_seconds=UNMEASURED_FLOAT,
                          materialize_seconds=UNMEASURED_FLOAT,
                          total_seconds=UNMEASURED_FLOAT)
        if table.num_rows:
            qm.steps = _static_step_metrics(_Bound(plan, table))
        note = ("  (empty input: eager path, nothing to measure)"
                if table.num_rows == 0 and metrics_enabled()
                else "  (metrics unavailable: set SRT_METRICS=1 "
                     "to measure)")
        qm.apply_opt(getattr(plan, "opt", None))
        return qm.render(header) + "\n" + note
    _, qm = analyze_plan(plan, table)
    text = qm.render(header)
    info = getattr(plan, "opt", None)
    if info is not None and info.rewrites:
        text += "\n" + info.render_diff()
    return text


# ---------------------------------------------------------------------------
# eager fallback (empty inputs; also the test oracle)
# ---------------------------------------------------------------------------

def _eager_grouping_sets(t: Table, step: GroupAggStep) -> Table:
    """Eager grouping sets: one eager group-by per level, levels stacked
    with null inactive keys + the grouping-id column (the oracle mirror
    of the compiled dense/sorted sets paths)."""
    from .. import ops
    from ..dtypes import STRING

    levels = []
    order = (list(step.keys) + [out for _, _, out in step.aggs]
             + [step.grouping_id])
    for active in step.sets:
        sub_keys = [step.keys[i] for i in active]
        tl = t
        if not sub_keys:
            tl = t.with_column("__gs_total__", Column(
                data=jnp.zeros(t.num_rows, jnp.int32), dtype=INT32))
            sub_keys = ["__gs_total__"]
        g = ops.groupby_agg(tl, sub_keys, list(step.aggs))
        if "__gs_total__" in g:
            g = g.drop(["__gs_total__"])
        rows = g.num_rows
        for i, key in enumerate(step.keys):
            if i in active:
                continue
            src = t[key]
            if src.dtype == STRING:
                from ..ops.strings import strings_from_pylist
                null_col = strings_from_pylist([None] * rows)
            else:
                null_col = Column(
                    data=jnp.zeros(rows, src.data.dtype),
                    validity=jnp.zeros(rows, jnp.bool_), dtype=src.dtype)
            g = g.with_column(key, null_col)
        g = g.with_column(step.grouping_id, Column(
            data=jnp.full(rows, len(step.keys) - len(active), jnp.int64),
            dtype=INT64))
        levels.append(g.select(order))
    return ops.concat_tables(levels)


def run_plan_eager(plan: Plan, table: Table) -> Table:
    """Execute a plan step-by-step with the eager ops layer.

    Semantics oracle for the compiled path (used directly for empty
    inputs, where XLA shapes degenerate)."""
    from .. import ops

    t = table
    for step in plan.steps:
        if isinstance(step, FilterStep):
            env = dict(t.items())
            t = ops.apply_boolean_mask(t, evaluate(step.pred, env))
        elif isinstance(step, ProjectStep):
            env = dict(t.items())

            def _ev(e):
                out = evaluate(e, env)
                return out if isinstance(out, Column) \
                    else lit_column(out, t.num_rows)

            if step.narrow:
                # Hidden engine columns survive narrowing, mirroring the
                # compiled path (_trace_project): rowid indirection,
                # string-agg surrogates, and lazy-facade attachments all
                # carry state the user-visible schema doesn't show.
                cols = [(nm, t[nm]) for nm in t.names
                        if _is_engine_hidden(nm)
                        and nm not in {n for n, _ in step.cols}]
                cols += [(nm, _ev(e)) for nm, e in step.cols]
                t = Table(cols)
            else:
                for nm, e in step.cols:
                    t = t.with_column(nm, _ev(e))
        elif isinstance(step, GroupAggStep):
            if step.sets is None:
                t = ops.groupby_agg(t, list(step.keys), list(step.aggs))
            else:
                t = _eager_grouping_sets(t, step)
        elif isinstance(step, UnionAllStep):
            branch = run_plan_eager(step.plan, step.table)
            names = list(t.names)
            if set(branch.names) != set(names):
                raise TypeError(
                    f"union_all schema mismatch: {sorted(t.names)} vs "
                    f"{sorted(branch.names)}")
            t = ops.concat_tables([t, branch.select(names)])
        elif isinstance(step, (JoinStep, JoinShuffledStep)):
            # Rename build keys to hidden temporaries first so a build-key
            # name equal to a PROBE column can never be suffix-renamed by
            # the eager join (the compiled path always drops build keys).
            hidden = {rn: f"__rk{i}__" for i, rn in enumerate(step.right_on)}
            build = step.table.rename(hidden)
            joined = ops.join(t, build, left_on=list(step.left_on),
                              right_on=[hidden[rn] for rn in step.right_on],
                              how=step.how)
            if step.how in ("inner", "left"):
                joined = joined.drop(
                    [h for h in hidden.values() if h in joined])
            t = joined
        elif isinstance(step, WindowStep):
            from ..ops import window as W
            if step.func == "row_number":
                c = W.row_number(t, list(step.partition_by),
                                 list(step.order_by) or None,
                                 list(step.ascending) or None)
            elif step.func == "rank":
                c = W.rank(t, list(step.partition_by), list(step.order_by),
                           list(step.ascending) or None)
            elif step.func == "dense_rank":
                c = W.dense_rank(t, list(step.partition_by),
                                 list(step.order_by),
                                 list(step.ascending) or None)
            elif step.func in ("lag", "lead"):
                f = W.lag if step.func == "lag" else W.lead
                c = f(t, step.value, list(step.partition_by),
                      list(step.order_by), offset=step.offset,
                      ascending=list(step.ascending) or None,
                      fill=step.fill)
            else:
                c = W.window_agg(t, step.value, step.func,
                                 list(step.partition_by),
                                 list(step.order_by) or None,
                                 list(step.ascending) or None,
                                 frame=step.frame)
            t = t.with_column(step.out, c)
        elif isinstance(step, SortStep):
            t = ops.sort_by(t, list(step.by), list(step.ascending),
                            list(step.nulls_first))
        elif isinstance(step, LimitStep):
            k = min(step.k, t.num_rows)
            t = t.gather(jnp.arange(k, dtype=jnp.int32))
        elif isinstance(step, TopKStep):
            t = ops.sort_by(t, list(step.by), list(step.ascending),
                            list(step.nulls_first))
            k = min(step.k, t.num_rows)
            t = t.gather(jnp.arange(k, dtype=jnp.int32))
        else:
            raise TypeError(f"unknown plan step {step!r}")
    return t
