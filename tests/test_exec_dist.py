"""Distributed plan execution tests (8 virtual CPU devices, conftest).

Oracle: a distributed plan over a sharded table must produce exactly the
same result as the same plan run locally on the unsharded table (which is
itself oracle-checked against the eager ops layer in test_exec.py).
"""

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu.exec import col, plan
from spark_rapids_tpu.parallel import make_flat_mesh, shard_table


def _table(rng, n=4003):
    return Table([
        ("k1", Column.from_numpy(rng.integers(0, 5, n).astype(np.int8),
                                 validity=rng.random(n) > 0.1)),
        ("k2", Column.from_numpy(rng.integers(0, 2, n).astype(np.bool_))),
        ("v", Column.from_numpy(rng.integers(-100, 100, n).astype(np.int64),
                                validity=rng.random(n) > 0.2)),
        ("f", Column.from_numpy(rng.normal(size=n))),
    ])


@pytest.fixture(scope="module")
def mesh():
    return make_flat_mesh()


class TestDistPlans:
    def test_dense_groupby_matches_local(self, rng, mesh):
        t = _table(rng)
        dist = shard_table(t, mesh)
        p = (plan().filter(col("v") > 0)
             .groupby_agg(["k1", "k2"],
                          [("v", "sum", "vs"), ("v", "count", "n"),
                           ("f", "mean", "fm"), ("v", "min", "vmin"),
                           ("v", "max", "vmax"), ("f", "var", "fv"),
                           ("f", "std", "fs"), ("v", "count_all", "ca")])
             .sort_by(["k1", "k2"]))
        got = p.run_dist(dist, mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_projection_and_join(self, rng, mesh):
        t = _table(rng)
        d = Table([("dk", Column.from_numpy(np.arange(5, dtype=np.int8))),
                   ("w", Column.from_numpy(rng.normal(size=5)))])
        p = (plan()
             .join_broadcast(d, left_on="k1", right_on="dk", how="left")
             .with_columns(z=col("f") * col("w").fill_null(1.0))
             .groupby_agg(["k1"], [("z", "sum", "zs")])
             .sort_by(["k1"]))
        got = p.run_dist(shard_table(t, mesh), mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_filter_only_returns_disttable(self, rng, mesh):
        from spark_rapids_tpu.parallel import collect
        from spark_rapids_tpu.parallel.mesh import DistTable
        t = _table(rng)
        p = plan().filter(col("v") > 0).with_columns(g=col("f") * 2.0)
        out = p.run_dist(shard_table(t, mesh), mesh)
        assert isinstance(out, DistTable)
        got = collect(out)
        want = p.run(t)
        # Shard padding permutes nothing: row order is preserved within
        # the contiguous deal-out, so direct equality applies.
        assert_tables_equal(want, got, rtol=1e-12, atol=1e-12)

    def test_sharded_sort_raises(self, rng, mesh):
        t = _table(rng)
        p = plan().sort_by(["v"])
        with pytest.raises(TypeError, match="sort"):
            p.run_dist(shard_table(t, mesh), mesh)

    def test_sharded_wide_groupby_raises(self, rng, mesh):
        n = 1000
        t = Table([
            ("k", Column.from_numpy(
                rng.integers(0, 1_000_000, n).astype(np.int64))),
            ("v", Column.from_numpy(rng.normal(size=n))),
        ])
        p = plan().groupby_agg(["k"], [("v", "sum", "s")])
        with pytest.raises(TypeError, match="dense-domain"):
            p.run_dist(shard_table(t, mesh), mesh)

    def test_padding_does_not_widen_domain(self, rng, mesh):
        # Keys in [300, 400]: the zero-filled padding slots must not drag
        # the probed domain down to [0, 400] (which would overflow
        # DENSE_MAX_CELLS and wrongly reject the distributed plan).
        n = 4003                                   # pads 5 zero slots
        t = Table([
            ("k", Column.from_numpy(
                (rng.integers(0, 101, n) + 300).astype(np.int64))),
            ("v", Column.from_numpy(rng.normal(size=n))),
        ])
        p = (plan().groupby_agg(["k"], [("v", "sum", "s")])
             .sort_by(["k"]))
        got = p.run_dist(shard_table(t, mesh), mesh)
        want = p.run(t)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_mesh_identity_in_cache(self, rng, mesh):
        import jax
        from spark_rapids_tpu.parallel import make_flat_mesh
        devs = jax.devices()
        m1 = make_flat_mesh(devs[:4])
        m2 = make_flat_mesh(devs[4:8])
        t = _table(rng, n=400)
        p = plan().groupby_agg(["k1"], [("v", "sum", "s")]).sort_by(["k1"])
        got1 = p.run_dist(shard_table(t, m1), m1)
        got2 = p.run_dist(shard_table(t, m2), m2)
        want = p.run(t)
        assert_tables_equal(want, got1)
        assert_tables_equal(want, got2)

    def test_empty_dist_table(self, rng, mesh):
        # shard_table pads an empty table to capacity with zero live rows;
        # the runner must fall back to the eager empty result, not raise.
        t = _table(rng, n=16).gather(np.zeros(0, np.int32))
        d0 = shard_table(t, mesh, capacity=2)
        p = plan().groupby_agg(["k1"], [("v", "sum", "s")])
        out = p.run_dist(d0, mesh)
        assert out.num_rows == 0

    def test_first_across_shards_raises(self, rng, mesh):
        t = _table(rng)
        p = plan().groupby_agg(["k1"], [("v", "first", "vf")])
        with pytest.raises(TypeError, match="first/last"):
            p.run_dist(shard_table(t, mesh), mesh)


def _row_multiset(t):
    from spark_rapids_tpu.parallel import collect
    from spark_rapids_tpu.parallel.mesh import DistTable
    if isinstance(t, DistTable):
        t = collect(t)
    d = t.to_pydict()
    names = sorted(d)
    return sorted(zip(*[d[nm] for nm in names]),
                  key=lambda r: tuple((x is None, x) for x in r))


class TestDistShuffledJoin:
    """Big-big join over the mesh: both sides hash-shuffled with
    all_to_all, merge-joined per shard (the q95 shape distributed)."""

    def _facts(self, rng, n=4003, m=3001, hi=300):
        left = Table([
            ("k", Column.from_numpy(rng.integers(0, hi, n).astype(np.int64),
                                    validity=rng.random(n) > 0.05)),
            ("lv", Column.from_numpy(
                rng.integers(-100, 100, n).astype(np.int64))),
        ])
        right = Table([
            ("rk", Column.from_numpy(rng.integers(0, hi, m).astype(np.int64),
                                     validity=rng.random(m) > 0.05)),
            ("rv", Column.from_numpy(rng.integers(0, 40, m).astype(np.int64),
                                     validity=rng.random(m) > 0.1)),
        ])
        return left, right

    def test_join_groupby_matches_local(self, rng, mesh):
        left, right = self._facts(rng)
        p = (plan()
             .filter(col("lv") > -50)
             .join_shuffled(right, left_on="k", right_on="rk")
             .groupby_agg(["rv"], [("lv", "sum", "s"), ("lv", "count", "c")])
             .sort_by(["rv"]))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert_tables_equal(want, got, rtol=1e-9, atol=1e-9)

    def test_join_only_multiset(self, rng, mesh):
        from spark_rapids_tpu.parallel import collect
        left, right = self._facts(rng)
        for how in ("inner", "left"):
            p = plan().join_shuffled(right, left_on="k", right_on="rk",
                                     how=how)
            got = collect(p.run_dist(shard_table(left, mesh), mesh))
            want = p.run(left)
            assert _row_multiset(got) == _row_multiset(want), how

    def test_shared_key_name(self, rng, mesh):
        left, right = self._facts(rng, n=1200, m=900)
        right = right.rename({"rk": "k"})
        p = (plan().join_shuffled(right, on="k")
             .groupby_agg(["rv"], [("lv", "sum", "s")])
             .sort_by(["rv"]))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert_tables_equal(want, got)

    def test_semi_raises_dist(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = plan().join_shuffled(right, left_on="k", right_on="rk",
                                 how="semi")
        with pytest.raises(TypeError, match="inner/left"):
            p.run_dist(shard_table(left, mesh), mesh)

    def test_join_after_groupby_raises_dist(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = (plan().groupby_agg(["k"], [("lv", "sum", "s")],
                                domains={"k": (0, 299)})
             .join_shuffled(right, left_on="k", right_on="rk"))
        with pytest.raises(TypeError, match="join first"):
            p.run_dist(shard_table(left, mesh), mesh)

    def test_empty_left_falls_back_eager(self, rng, mesh):
        left, right = self._facts(rng, n=16, m=8)
        empty = left.gather(np.zeros(0, np.int32))
        d0 = shard_table(empty, mesh, capacity=2)
        p = (plan().join_shuffled(right, left_on="k", right_on="rk")
             .groupby_agg(["rv"], [("lv", "sum", "s")]))
        out = p.run_dist(d0, mesh)
        assert out.num_rows == 0

    def test_empty_right_falls_back_eager(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=8)
        right0 = right.gather(np.zeros(0, np.int32))
        for how in ("inner", "left"):
            p = plan().join_shuffled(right0, left_on="k", right_on="rk",
                                     how=how)
            got = p.run_dist(shard_table(left, mesh), mesh)
            want = p.run(left)
            assert _row_multiset(got) == _row_multiset(want), how

    def test_prefix_filters_all_rows(self, rng, mesh):
        left, right = self._facts(rng, n=400, m=300)
        p = (plan().filter(col("lv") > 10_000)      # drops every row
             .join_shuffled(right, left_on="k", right_on="rk"))
        got = p.run_dist(shard_table(left, mesh), mesh)
        want = p.run(left)
        assert _row_multiset(got) == _row_multiset(want)

    def test_empty_input_keeps_disttable_contract(self, rng, mesh):
        from spark_rapids_tpu.parallel.mesh import DistTable
        left, _ = self._facts(rng, n=16, m=8)
        empty = left.gather(np.zeros(0, np.int32))
        d0 = shard_table(empty, mesh, capacity=2)
        # Row-sharded-ending plan over an empty input: still a DistTable.
        out = plan().filter(col("lv") > 0).run_dist(d0, mesh)
        assert isinstance(out, DistTable)
        assert out.num_rows() == 0


# ---------------------------------------------------------------------------
# Mesh recovery ladder: shard-targeted faults, per-shard split, degradation
# ---------------------------------------------------------------------------

import json
import time

from spark_rapids_tpu.obs import last_query_metrics, registry, timeline
from spark_rapids_tpu.resilience import (DistStallError,
                                         ExecutionRecoveryError,
                                         recovery_stats, reset_faults)


@pytest.fixture(autouse=True)
def _fresh_faults(monkeypatch):
    """No armed faults, zero backoff: mesh-fault tests never leak their
    injection state (a parked stall worker is released by reset_faults)."""
    monkeypatch.delenv("SRT_FAULT", raising=False)
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    reset_faults()
    yield
    reset_faults()


def _res_table(n=4003, seed=0):
    """Integer values (nullable) so every aggregate is exact regardless of
    merge order — faulted runs must be bit-identical, not just close."""
    r = np.random.default_rng(seed)
    return Table([
        ("k", Column.from_numpy(r.integers(0, 5, n).astype(np.int64))),
        ("v", Column.from_numpy(r.integers(-100, 100, n).astype(np.int64),
                                validity=r.random(n) > 0.2)),
    ])


def _rep_plan():
    """Replicated-ending: filter + dense group-by (static domains so the
    combine split rung has a batch-invariant accumulator layout)."""
    return (plan().filter(col("v") > 0)
            .groupby_agg(["k"], [("v", "sum", "s"), ("v", "count", "c"),
                                 ("v", "max", "m")],
                         domains={"k": (0, 4)}))


def _sharded_plan():
    """Row-sharded-ending: pure filter/project, returns a DistTable."""
    return plan().filter(col("v") > 0).with_columns(w=col("v") * 2)


def _join_right(m=3001, seed=1):
    r = np.random.default_rng(seed)
    return Table([
        ("rk", Column.from_numpy(r.integers(0, 5, m).astype(np.int64))),
        ("rv", Column.from_numpy(r.integers(0, 40, m).astype(np.int64))),
    ])


def _join_plan(right):
    """Shuffled-join shape: all_to_all both sides, merge-join per shard,
    then a distributed group-by on the joined rows."""
    return (plan().join_shuffled(right, left_on="k", right_on="rk")
            .groupby_agg(["rv"], [("v", "sum", "s"), ("v", "count", "c")])
            .sort_by(["rv"]))


class TestMeshRecoveryLadder:
    """Every dist fault site recovers bit-identically through the mesh
    ladder, for all three plan shapes the dist layer executes."""

    @pytest.mark.parametrize("site", ("dist-dispatch", "collective"))
    def test_replicated_plan_recovers(self, monkeypatch, mesh, site):
        t = _res_table()
        p = _rep_plan()
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == oracle
        d = recovery_stats().delta(before)
        assert d["dist_retries"] >= 1 and d["dist_evictions"] >= 1
        # dist rungs also bump the totals (the dist block is a subset).
        assert d["retries"] >= d["dist_retries"]

    def test_row_sharded_plan_recovers(self, monkeypatch, mesh):
        from spark_rapids_tpu.parallel import collect
        t = _res_table()
        p = _sharded_plan()
        oracle = _row_multiset(collect(p.run_dist(shard_table(t, mesh),
                                                  mesh)))
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        got = collect(p.run_dist(shard_table(t, mesh), mesh))
        assert _row_multiset(got) == oracle
        assert recovery_stats().delta(before)["dist_retries"] >= 1

    @pytest.mark.parametrize("shard", (0, 3, 7))
    def test_shard_targeted_fault_recovers(self, monkeypatch, mesh, shard):
        # One shard of eight fails; the ladder recovers the whole program.
        t = _res_table()
        p = _rep_plan()
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT",
                           f"oom:dist-dispatch:1:shard={shard}")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == oracle
        assert recovery_stats().delta(before)["dist_retries"] >= 1

    def test_shard_selector_misses_other_shards(self, monkeypatch, mesh):
        # A spec pinned to a shard the mesh never reaches stays armed:
        # no injection, no recovery, clean result.
        t = _res_table()
        p = _rep_plan()
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1:shard=64")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == oracle
        d = recovery_stats().delta(before)
        assert d["faults_injected"] == 0 and d["dist_retries"] == 0

    @pytest.mark.parametrize("site",
                             ("shuffle", "collective", "dist-dispatch"))
    def test_shuffled_join_plan_recovers(self, monkeypatch, mesh, site):
        t = _res_table()
        right = _join_right()
        p = _join_plan(right)
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT", f"oom:{site}:1:shard=3")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == oracle
        assert recovery_stats().delta(before)["dist_retries"] >= 1


class TestMeshSplitRung:
    def test_concat_split_bit_identical(self, monkeypatch, mesh):
        from spark_rapids_tpu.parallel import collect
        t = _res_table()
        p = _sharded_plan()
        oracle = collect(p.run_dist(shard_table(t, mesh), mesh)).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "0")
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        got = collect(p.run_dist(shard_table(t, mesh), mesh))
        # Slot order is preserved shard-wise, so direct equality applies.
        assert got.to_pydict() == oracle
        d = recovery_stats().delta(before)
        assert d["dist_splits"] >= 1 and d["splits"] >= d["dist_splits"]

    def test_combine_split_bit_identical(self, monkeypatch, mesh):
        t = _res_table()
        p = _rep_plan()
        oracle = _row_multiset(p.run_dist(shard_table(t, mesh), mesh))
        monkeypatch.setenv("SRT_RETRY_MAX", "0")
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1")
        reset_faults()
        before = recovery_stats().snapshot()
        got = p.run_dist(shard_table(t, mesh), mesh)
        assert _row_multiset(got) == oracle
        assert recovery_stats().delta(before)["dist_splits"] >= 1

    def test_recursive_split_shrinks_until_it_fits(self, monkeypatch, mesh):
        from spark_rapids_tpu.parallel import collect
        t = _res_table()
        p = _sharded_plan()
        oracle = collect(p.run_dist(shard_table(t, mesh), mesh)).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "0")
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:3")
        reset_faults()
        before = recovery_stats().snapshot()
        got = collect(p.run_dist(shard_table(t, mesh), mesh))
        assert got.to_pydict() == oracle
        assert recovery_stats().delta(before)["dist_splits"] >= 2


class TestMeshDegradation:
    def _unsplittable(self):
        # sort after the group-by blocks both split modes.
        return _rep_plan().sort_by(["k"])

    def test_collect_fallback_completes_single_chip(self, monkeypatch,
                                                    mesh):
        t = _res_table()
        p = self._unsplittable()
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_DIST_FALLBACK", "collect")
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:99")
        reset_faults()
        before = recovery_stats().snapshot()
        with timeline.recording() as rec:
            got = p.run_dist(shard_table(t, mesh), mesh)
        assert got.to_pydict() == oracle
        assert recovery_stats().delta(before)["dist_fallbacks"] >= 1
        names = [e["name"] for e in rec.events()]
        assert "recovery.dist.fallback" in names
        assert "recovery.dist.fallback_done" in names

    def test_dist_join_fallback(self, monkeypatch, mesh):
        # A shuffled join cannot split per shard: its exhaustion goes
        # straight to the collect fallback.
        t = _res_table()
        p = _join_plan(_join_right())
        oracle = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_DIST_FALLBACK", "collect")
        monkeypatch.setenv("SRT_FAULT", "oom:shuffle:99")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == oracle
        assert recovery_stats().delta(before)["dist_fallbacks"] >= 1

    def test_exhausted_ladder_names_every_rung(self, monkeypatch, mesh):
        t = _res_table()
        p = self._unsplittable()
        monkeypatch.delenv("SRT_DIST_FALLBACK", raising=False)
        monkeypatch.setenv("SRT_RETRY_MAX", "1")
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:99")
        reset_faults()
        with pytest.raises(ExecutionRecoveryError) as ei:
            p.run_dist(shard_table(t, mesh), mesh)
        err = ei.value
        assert err.site == "dist-dispatch"
        assert "RESOURCE_EXHAUSTED" in str(err.__cause__)
        msg = str(err)
        assert "evict-caches" in msg and "retry" in msg
        assert "split-unavailable" in msg
        assert "collect-fallback" in msg and "SRT_DIST_FALLBACK" in msg

    def test_stall_watchdog_on_collect(self, monkeypatch, mesh):
        from spark_rapids_tpu.parallel import collect
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "0.3")
        monkeypatch.setenv("SRT_FAULT", "stall:collect:1")
        reset_faults()
        t0 = time.monotonic()
        with pytest.raises(DistStallError, match="SRT_DIST_TIMEOUT"):
            collect(shard_table(_res_table(n=64), mesh))
        assert time.monotonic() - t0 < 5.0

    def test_stall_watchdog_on_dispatch(self, monkeypatch, mesh):
        monkeypatch.setenv("SRT_DIST_TIMEOUT", "0.3")
        monkeypatch.setenv("SRT_FAULT", "stall:dist-dispatch:1:shard=5")
        reset_faults()
        t = _res_table()
        t0 = time.monotonic()
        with pytest.raises(DistStallError):
            _sharded_plan().run_dist(shard_table(t, mesh), mesh)
        assert time.monotonic() - t0 < 5.0


class TestDistCompileCache:
    def test_dist_cache_is_bounded_lru(self, monkeypatch, mesh):
        from spark_rapids_tpu.exec import dist as dist_mod
        monkeypatch.setenv("SRT_METRICS", "1")  # eviction counters live
        monkeypatch.setenv("SRT_COMPILE_CACHE_CAP", "2")
        dist_mod._DIST_COMPILED.clear()
        t = _res_table(n=400)
        d = shard_table(t, mesh)
        before = registry().snapshot()
        plans = [plan().filter(col("v") > i).with_columns(w=col("v") * 2)
                 for i in (0, 10, 20)]
        for p in plans:
            p.run_dist(d, mesh)
        assert len(dist_mod._DIST_COMPILED) <= 2
        snap = registry().snapshot()
        evicted = (snap.get("dist.compile_cache.evictions", 0)
                   - before.get("dist.compile_cache.evictions", 0))
        assert evicted >= 1
        assert snap.get("dist.compile_cache.size") == \
            len(dist_mod._DIST_COMPILED)
        registry().reset()

    def test_evict_clears_every_dist_cache(self, monkeypatch, mesh):
        from spark_rapids_tpu.exec import dist as dist_mod
        from spark_rapids_tpu.parallel import mesh as mesh_mod
        from spark_rapids_tpu.resilience.recovery import evict_device_caches
        # Metered run: the live-count cache (_LIVE_COUNT) fills on the
        # metrics path, so the evict must drop it too.
        monkeypatch.setenv("SRT_METRICS", "1")
        registry().reset()
        t = _res_table(n=400)
        # Keep the DistTables alive: live-count entries are weakref-guarded
        # on the row-mask buffer and self-evict when it is collected.
        d1, d2 = shard_table(t, mesh), shard_table(t, mesh)
        _rep_plan().run_dist(d1, mesh)
        _join_plan(_join_right(m=300)).run_dist(d2, mesh)
        assert dist_mod._DIST_COMPILED and dist_mod._LIVE_COUNT
        assert mesh_mod._DIST_PROGRAMS     # shuffle/join local programs
        expected = (len(dist_mod._DIST_COMPILED)
                    + len(dist_mod._LIVE_COUNT)
                    + len(mesh_mod._DIST_PROGRAMS))
        dropped = evict_device_caches()
        assert dropped >= expected
        assert not dist_mod._DIST_COMPILED
        assert not dist_mod._LIVE_COUNT
        assert not mesh_mod._DIST_PROGRAMS
        registry().reset()

    def test_query_metrics_records_dist_block(self, monkeypatch, mesh):
        monkeypatch.setenv("SRT_METRICS", "1")
        registry().reset()
        t = _res_table()
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1")
        reset_faults()
        _rep_plan().run_dist(shard_table(t, mesh), mesh)
        payload = json.loads(last_query_metrics().to_json())
        assert payload["mode"] == "dist"
        assert payload["schema_version"] == 11
        rec = payload["recovery"]["dist"]
        assert rec["retries"] >= 1 and rec["cache_evictions"] >= 1
        assert "recovery.dist:" in last_query_metrics().render()
        registry().reset()


# ---------------------------------------------------------------------------
# faulted-dist CI lane (ci/premerge-build.sh exports
# SRT_FAULT=oom:dist-dispatch:1:shard=2 + SRT_METRICS=1; the tests pin
# their own spec so they also pass standalone)
# ---------------------------------------------------------------------------

@pytest.mark.faulted_dist
class TestFaultedDistSmoke:
    def test_dist_dispatch_fault_golden(self, monkeypatch, mesh):
        monkeypatch.setenv("SRT_METRICS", "1")
        registry().reset()
        t = _res_table()
        p = _rep_plan()
        monkeypatch.delenv("SRT_FAULT", raising=False)
        reset_faults()
        golden = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT", "oom:dist-dispatch:1:shard=2")
        reset_faults()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == golden
        rec = json.loads(last_query_metrics().to_json())["recovery"]["dist"]
        assert rec["retries"] >= 1 and rec["cache_evictions"] >= 1
        snap = registry().snapshot()
        assert snap.get("recovery.dist.retries", 0) >= 1
        assert snap.get("resilience.faults_injected", 0) >= 1
        registry().reset()

    def test_shuffled_join_fault_golden(self, monkeypatch, mesh):
        t = _res_table()
        p = _join_plan(_join_right())
        monkeypatch.delenv("SRT_FAULT", raising=False)
        reset_faults()
        golden = p.run_dist(shard_table(t, mesh), mesh).to_pydict()
        monkeypatch.setenv("SRT_FAULT", "oom:shuffle:1:shard=2")
        reset_faults()
        before = recovery_stats().snapshot()
        assert p.run_dist(shard_table(t, mesh), mesh).to_pydict() == golden
        assert recovery_stats().delta(before)["dist_retries"] >= 1
