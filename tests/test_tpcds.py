"""TPC-DS query-bank oracle tests.

Every bank query runs at a small scale and is checked against an
independent pandas re-implementation of the same semantics (the bank
must not be its own oracle; mirrors the reference strategy of full-table
equality against a known-good engine, SURVEY.md §4).
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.models import tpcds
from spark_rapids_tpu.models.tpcds_queries import QUERIES

#: compile-heavy module: full tier only (smoke = -m 'not full').
pytestmark = pytest.mark.full

SF_ROWS = 20_000


@pytest.fixture(scope="module")
def data():
    return tpcds.generate(SF_ROWS, seed=7)


@pytest.fixture(scope="module")
def pdf(data):
    """The same tables as pandas DataFrames (None -> NaN/NA)."""
    out = {}
    for nm in data.names():
        t = getattr(data, nm)
        out[nm] = pd.DataFrame(
            {c: pd.array(t[c].to_pylist()) for c in t.names})
    return out


def _assert_frame(got, want, float_cols=(), sort_check_cols=None):
    """Compare a result Table against a pandas frame column-by-column.

    ``sort_check_cols``: when the query's ORDER BY includes a float key,
    ties (and float rounding) can legally reorder rows; pass the subset
    of columns that define a total order to re-sort both sides before
    comparison."""
    got_df = pd.DataFrame({c: pd.array(got[c].to_pylist())
                           for c in got.names})
    assert set(got_df.columns) == set(want.columns), \
        f"columns: {sorted(got_df.columns)} vs {sorted(want.columns)}"
    want = want[list(got_df.columns)]     # engine column order wins
    assert len(got_df) == len(want), f"rows: {len(got_df)} vs {len(want)}"
    if sort_check_cols:
        got_df = got_df.sort_values(sort_check_cols).reset_index(drop=True)
        want = want.sort_values(sort_check_cols).reset_index(drop=True)
    else:
        want = want.reset_index(drop=True)
    for c in want.columns:
        g, w = got_df[c], want[c]
        if c in float_cols:
            gn = g.isna().to_numpy(dtype=bool)
            wn = w.isna().to_numpy(dtype=bool)
            np.testing.assert_array_equal(gn, wn, err_msg=f"nulls in {c}")
            np.testing.assert_allclose(
                g.to_numpy(dtype=float)[~gn], w.to_numpy(dtype=float)[~wn],
                rtol=1e-9, atol=1e-9, err_msg=c)
        else:
            assert g.tolist() == w.tolist(), f"column {c}"


class TestBatchA:
    def test_q3(self, data, pdf):
        got = QUERIES["q3"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        j = (ss.merge(dd[dd.d_moy == 11][["d_date_sk", "d_year"]],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manufact_id == 28][["i_item_sk", "i_brand_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id"], dropna=False)
             ["ss_ext_sales_price"].sum(min_count=1).reset_index()
             .rename(columns={"ss_ext_sales_price": "sum_agg"}))
        g["i_brand"] = [tpcds.BRANDS[i - 1] for i in g.i_brand_id]
        g = (g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                           ascending=[True, False, True]).head(100)
             [["d_year", "i_brand_id", "sum_agg", "i_brand"]])
        _assert_frame(got, g, float_cols=("sum_agg",),
                      sort_check_cols=["d_year", "i_brand_id"])

    def test_q7(self, data, pdf):
        got = QUERIES["q7"](data)
        ss, cd, dd, pr = (pdf["store_sales"], pdf["customer_demographics"],
                          pdf["date_dim"], pdf["promotion"])
        it = pdf["item"]
        cds = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")].cd_demo_sk
        dds = dd[dd.d_year == 1998].d_date_sk
        prs = pr[(pr.p_channel_email == "N")
                 | (pr.p_channel_event == "N")].p_promo_sk
        j = ss[ss.ss_cdemo_sk.isin(cds) & ss.ss_sold_date_sk.isin(dds)
               & ss.ss_promo_sk.isin(prs)]
        g = (j.groupby("ss_item_sk", dropna=False)
             .agg(agg1=("ss_quantity", "mean"),
                  agg2=("ss_list_price", "mean"),
                  agg3=("ss_coupon_amt", "mean"),
                  agg4=("ss_sales_price", "mean")).reset_index())
        g = g.merge(it[["i_item_sk", "i_item_id"]], left_on="ss_item_sk",
                    right_on="i_item_sk")[
            ["ss_item_sk", "agg1", "agg2", "agg3", "agg4", "i_item_id"]]
        g = g.sort_values("ss_item_sk").head(100)
        _assert_frame(got, g, float_cols=("agg1", "agg2", "agg3", "agg4"))

    def test_q26(self, data, pdf):
        got = QUERIES["q26"](data)
        cs, cd, dd, pr = (pdf["catalog_sales"], pdf["customer_demographics"],
                          pdf["date_dim"], pdf["promotion"])
        it = pdf["item"]
        cds = cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "M")
                 & (cd.cd_education_status == "College")].cd_demo_sk
        dds = dd[dd.d_year == 1999].d_date_sk
        prs = pr[(pr.p_channel_email == "N")
                 | (pr.p_channel_event == "N")].p_promo_sk
        j = cs[cs.cs_bill_cdemo_sk.isin(cds) & cs.cs_sold_date_sk.isin(dds)
               & cs.cs_promo_sk.isin(prs)]
        g = (j.groupby("cs_item_sk", dropna=False)
             .agg(agg1=("cs_quantity", "mean"),
                  agg2=("cs_list_price", "mean"),
                  agg3=("cs_coupon_amt", "mean"),
                  agg4=("cs_sales_price", "mean")).reset_index())
        g = g.merge(it[["i_item_sk", "i_item_id"]], left_on="cs_item_sk",
                    right_on="i_item_sk")[
            ["cs_item_sk", "agg1", "agg2", "agg3", "agg4", "i_item_id"]]
        g = g.sort_values("cs_item_sk").head(100)
        _assert_frame(got, g, float_cols=("agg1", "agg2", "agg3", "agg4"))

    def test_q42(self, data, pdf):
        got = QUERIES["q42"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        j = (ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)]
                      [["d_date_sk", "d_year"]],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 1][["i_item_sk", "i_category_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_category_id"], dropna=False)
             ["ss_ext_sales_price"].sum(min_count=1).reset_index()
             .rename(columns={"ss_ext_sales_price": "sum_agg"}))
        g["i_category"] = [tpcds.CATEGORIES[i - 1] for i in g.i_category_id]
        g = (g.sort_values(["sum_agg", "d_year", "i_category_id"],
                           ascending=[False, True, True]).head(100)
             [["d_year", "i_category_id", "sum_agg", "i_category"]])
        _assert_frame(got, g, float_cols=("sum_agg",),
                      sort_check_cols=["d_year", "i_category_id"])

    def test_q43(self, data, pdf):
        got = QUERIES["q43"](data)
        ss, dd, st = pdf["store_sales"], pdf["date_dim"], pdf["store"]
        j = ss.merge(dd[dd.d_year == 1998][["d_date_sk", "d_dow"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        names = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")
        for i, nm in enumerate(names):
            j[f"{nm}_sales"] = j.ss_sales_price.where(j.d_dow == i)
        g = (j.groupby("ss_store_sk", dropna=False)
             .agg(**{f"{nm}_sales": (f"{nm}_sales",
                                     lambda s: s.sum(min_count=1))
                     for nm in names}).reset_index())
        g = g.merge(st[["s_store_sk", "s_store_id"]], left_on="ss_store_sk",
                    right_on="s_store_sk")[
            ["ss_store_sk"] + [f"{nm}_sales" for nm in names]
            + ["s_store_id"]]
        g = g.sort_values("ss_store_sk").head(100)
        _assert_frame(got, g,
                      float_cols=tuple(f"{nm}_sales" for nm in names))

    def test_q52(self, data, pdf):
        got = QUERIES["q52"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 1998)]
                      [["d_date_sk", "d_year"]],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[["i_item_sk", "i_brand_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["d_year", "i_brand_id"], dropna=False)
             ["ss_ext_sales_price"].sum(min_count=1).reset_index()
             .rename(columns={"ss_ext_sales_price": "ext_price"}))
        g["i_brand"] = [tpcds.BRANDS[i - 1] for i in g.i_brand_id]
        g = (g.sort_values(["d_year", "ext_price", "i_brand_id"],
                           ascending=[True, False, True]).head(100)
             [["d_year", "i_brand_id", "ext_price", "i_brand"]])
        _assert_frame(got, g, float_cols=("ext_price",),
                      sort_check_cols=["d_year", "i_brand_id"])

    def test_q55(self, data, pdf):
        got = QUERIES["q55"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        dds = dd[(dd.d_moy == 11) & (dd.d_year == 1999)].d_date_sk
        j = (ss[ss.ss_sold_date_sk.isin(dds)]
             .merge(it[it.i_manager_id == 36][["i_item_sk", "i_brand_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby("i_brand_id", dropna=False)["ss_ext_sales_price"]
             .sum(min_count=1).reset_index()
             .rename(columns={"ss_ext_sales_price": "ext_price"}))
        g["i_brand"] = [tpcds.BRANDS[i - 1] for i in g.i_brand_id]
        g = (g.sort_values(["ext_price", "i_brand_id"],
                           ascending=[False, True]).head(100)
             [["i_brand_id", "ext_price", "i_brand"]])
        _assert_frame(got, g, float_cols=("ext_price",),
                      sort_check_cols=["i_brand_id"])

    def test_q88(self, data, pdf):
        got = QUERIES["q88"](data)
        ss, hd, st, td = (pdf["store_sales"],
                          pdf["household_demographics"], pdf["store"],
                          pdf["time_dim"])
        hds = hd[((hd.hd_dep_count == 3) & hd.hd_vehicle_count.between(0, 2))
                 | ((hd.hd_dep_count == 0)
                    & hd.hd_vehicle_count.between(1, 3))].hd_demo_sk
        sts = st[st.s_store_name == "store3"].s_store_sk
        j = (ss[ss.ss_hdemo_sk.isin(hds) & ss.ss_store_sk.isin(sts)]
             .merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk"))
        j["half_id"] = ((j.t_hour - 8) * 2
                        + (j.t_minute >= 30).astype(int) - 1)
        j = j[j.half_id.between(0, 7)]
        g = (j.groupby("half_id")["t_hour"].count().reset_index()
             .rename(columns={"t_hour": "cnt"})
             .sort_values("half_id").reset_index(drop=True))
        g["half_id"] = g["half_id"].astype("int64")
        g["cnt"] = g["cnt"].astype("int64")
        _assert_frame(got, g)

    def test_q96(self, data, pdf):
        got = QUERIES["q96"](data)
        ss, hd, st, td = (pdf["store_sales"],
                          pdf["household_demographics"], pdf["store"],
                          pdf["time_dim"])
        hds = hd[hd.hd_dep_count == 7].hd_demo_sk
        tds = td[(td.t_hour == 20) & (td.t_minute >= 30)].t_time_sk
        sts = st[st.s_store_name == "store1"].s_store_sk
        n = len(ss[ss.ss_hdemo_sk.isin(hds) & ss.ss_sold_time_sk.isin(tds)
                   & ss.ss_store_sk.isin(sts)])
        assert got["cnt"].to_pylist() == [n]


class TestBatchB:
    def test_q15(self, data, pdf):
        got = QUERIES["q15"](data)
        cs, cu, ca, dd = (pdf["catalog_sales"], pdf["customer"],
                          pdf["customer_address"], pdf["date_dim"])
        zips = [85669, 86197, 88274, 83405, 86475, 85392, 85460, 80348,
                81792]
        ca = ca.copy()
        ca["ca_flag"] = (ca.ca_zip5.isin(zips)
                         | ca.ca_state.isin(["CA", "WA", "GA"])).astype(int)
        dds = dd[(dd.d_qoy == 2) & (dd.d_year == 1999)].d_date_sk
        j = (cs.merge(cu[["c_customer_sk", "c_current_addr_sk"]],
                      left_on="cs_bill_customer_sk",
                      right_on="c_customer_sk")
             .merge(ca[["ca_address_sk", "ca_zip5", "ca_flag"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk"))
        j = j[j.cs_sold_date_sk.isin(dds)]
        j = j[(j.ca_flag == 1) | (j.cs_sales_price > 500.0)]
        g = (j.groupby("ca_zip5", dropna=False)["cs_sales_price"]
             .sum(min_count=1).reset_index()
             .rename(columns={"cs_sales_price": "total_price"}))
        g = g.sort_values("ca_zip5").head(100)
        _assert_frame(got, g, float_cols=("total_price",))

    def test_q19(self, data, pdf):
        got = QUERIES["q19"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        cu, ca, st = pdf["customer"], pdf["customer_address"], pdf["store"]
        dds = dd[(dd.d_moy == 11) & (dd.d_year == 1998)].d_date_sk
        j = (ss[ss.ss_sold_date_sk.isin(dds)]
             .merge(it[it.i_manager_id == 7][["i_item_sk", "i_brand_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk")
             .merge(cu[["c_customer_sk", "c_current_addr_sk"]],
                    left_on="ss_customer_sk", right_on="c_customer_sk")
             .merge(ca[["ca_address_sk", "ca_zip5"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
             .merge(st[["s_store_sk", "s_zip5"]],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        j = j[j.ca_zip5 != j.s_zip5]
        g = (j.groupby("i_brand_id", dropna=False)["ss_ext_sales_price"]
             .sum(min_count=1).reset_index()
             .rename(columns={"ss_ext_sales_price": "ext_price"}))
        g["i_brand"] = [tpcds.BRANDS[i - 1] for i in g.i_brand_id]
        g = (g.sort_values(["ext_price", "i_brand_id"],
                           ascending=[False, True]).head(100))
        _assert_frame(got, g, float_cols=("ext_price",),
                      sort_check_cols=["i_brand_id"])

    def test_q28(self, data, pdf):
        got = QUERIES["q28"](data)
        ss = pdf["store_sales"].copy()
        buckets = [(0, 5, 8.0, 4.0, 7.0), (6, 10, 9.0, 9.0, 3.0),
                   (11, 15, 7.0, 2.0, 8.0), (16, 20, 6.0, 6.0, 6.0),
                   (21, 25, 8.5, 1.0, 4.0), (26, 30, 9.5, 8.0, 5.0)]
        qn = ss.ss_quantity.to_numpy(dtype=float)
        lp = ss.ss_list_price.to_numpy(dtype=float)
        cp = ss.ss_coupon_amt.to_numpy(dtype=float)
        wc = ss.ss_ext_wholesale_cost.to_numpy(dtype=float)
        bucket = np.full(len(ss), -1)
        for i, (qlo, qhi, lpl, cpl, wcl) in enumerate(buckets):
            cond = ((qn >= qlo) & (qn <= qhi)
                    & (((lp >= lpl) & (lp <= lpl + 60))
                       | ((cp >= cpl) & (cp <= cpl + 20))
                       | ((wc >= wcl) & (wc <= wcl + 40))))
            bucket = np.where((bucket < 0) & cond, i, bucket)
        ss["bucket"] = bucket
        j = ss[ss.bucket >= 0]
        g = (j.groupby("bucket")
             .agg(avg_lp=("ss_list_price", "mean"),
                  cnt_lp=("ss_list_price", "count"),
                  uniq_lp=("ss_list_price", "nunique")).reset_index()
             .sort_values("bucket").reset_index(drop=True))
        g["bucket"] = g.bucket.astype("int64")
        g["cnt_lp"] = g.cnt_lp.astype("int64")
        g["uniq_lp"] = g.uniq_lp.astype("int64")
        _assert_frame(got, g, float_cols=("avg_lp",))

    def test_q48(self, data, pdf):
        got = QUERIES["q48"](data)
        ss, cd, ca, dd = (pdf["store_sales"],
                          pdf["customer_demographics"],
                          pdf["customer_address"], pdf["date_dim"])
        cd = cd.copy()
        cd["cd_tag"] = np.select(
            [(cd.cd_marital_status == "M")
             & (cd.cd_education_status == "4 yr Degree"),
             (cd.cd_marital_status == "D")
             & (cd.cd_education_status == "2 yr Degree"),
             (cd.cd_marital_status == "S")
             & (cd.cd_education_status == "College")], [1, 2, 3], 0)
        ca = ca.copy()
        ca["ca_tag"] = np.select(
            [ca.ca_state.isin(["CA", "OH", "TX"]),
             ca.ca_state.isin(["OR", "NY", "WA"]),
             ca.ca_state.isin(["GA", "TN", "IL"])], [1, 2, 3], 0)
        dds = dd[dd.d_year == 1999].d_date_sk
        j = (ss[ss.ss_sold_date_sk.isin(dds)]
             .merge(cd[["cd_demo_sk", "cd_tag"]], left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
             .merge(ca[["ca_address_sk", "ca_tag"]], left_on="ss_addr_sk",
                    right_on="ca_address_sk"))
        sp = j.ss_sales_price.to_numpy(dtype=float)
        npf = j.ss_net_profit.to_numpy(dtype=float)
        c1 = (((j.cd_tag == 1) & (sp >= 100) & (sp <= 150))
              | ((j.cd_tag == 2) & (sp >= 50) & (sp <= 100))
              | ((j.cd_tag == 3) & (sp >= 150) & (sp <= 200)))
        c2 = (((j.ca_tag == 1) & (npf >= 0) & (npf <= 2000))
              | ((j.ca_tag == 2) & (npf >= 150) & (npf <= 3000))
              | ((j.ca_tag == 3) & (npf >= 50) & (npf <= 25000)))
        want = j[c1 & c2].ss_quantity.sum()
        assert got["qty_sum"].to_pylist() == [int(want)]

    def test_q61(self, data, pdf):
        got = QUERIES["q61"](data)
        ss, dd, it, st = (pdf["store_sales"], pdf["date_dim"],
                          pdf["item"], pdf["store"])
        pr, cu, ca = (pdf["promotion"], pdf["customer"],
                      pdf["customer_address"])
        dds = dd[(dd.d_year == 1998) & (dd.d_moy == 11)].d_date_sk
        its = it[it.i_category == "Jewelry"].i_item_sk
        sts = st[st.s_gmt_offset == -5.0].s_store_sk
        cas = ca[ca.ca_gmt_offset == -5.0].ca_address_sk
        prs = pr[(pr.p_channel_dmail == "Y") | (pr.p_channel_email == "Y")
                 | (pr.p_channel_event == "Y")].p_promo_sk
        base = (ss[ss.ss_sold_date_sk.isin(dds) & ss.ss_item_sk.isin(its)
                   & ss.ss_store_sk.isin(sts)]
                .merge(cu[["c_customer_sk", "c_current_addr_sk"]],
                       left_on="ss_customer_sk", right_on="c_customer_sk"))
        base = base[base.c_current_addr_sk.isin(cas)]
        total = base.ss_ext_sales_price.sum()
        promo = base[base.ss_promo_sk.isin(prs)].ss_ext_sales_price.sum()
        g = got.to_pydict()
        np.testing.assert_allclose(g["promotions"][0], promo, rtol=1e-9)
        np.testing.assert_allclose(g["total"][0], total, rtol=1e-9)

    def test_q65(self, data, pdf):
        got = QUERIES["q65"](data)
        ss, dd, st, it = (pdf["store_sales"], pdf["date_dim"],
                          pdf["store"], pdf["item"])
        dds = dd[dd.d_month_seq.between(3, 14)].d_date_sk
        sc = (ss[ss.ss_sold_date_sk.isin(dds)]
              .groupby(["ss_store_sk", "ss_item_sk"], dropna=False)
              ["ss_sales_price"].sum(min_count=1).reset_index()
              .rename(columns={"ss_sales_price": "revenue"}))
        sb = (sc.groupby("ss_store_sk", dropna=False)["revenue"].mean()
              .reset_index().rename(columns={"revenue": "ave"}))
        j = sc.merge(sb, on="ss_store_sk")
        j = j[j.revenue <= 0.1 * j.ave]
        j = (j.merge(st[["s_store_sk", "s_store_name"]],
                     left_on="ss_store_sk", right_on="s_store_sk")
             .merge(it[["i_item_sk", "i_current_price"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        j = (j.sort_values(["ss_store_sk", "ss_item_sk"]).head(100)
             [["ss_store_sk", "ss_item_sk", "revenue", "ave",
               "s_store_name", "i_current_price"]])
        _assert_frame(got, j, float_cols=("revenue", "ave",
                                          "i_current_price"))

    def test_q68(self, data, pdf):
        got = QUERIES["q68"](data)
        ss, dd, st, hd = (pdf["store_sales"], pdf["date_dim"],
                          pdf["store"], pdf["household_demographics"])
        cu, ca = pdf["customer"], pdf["customer_address"]
        dds = dd[dd.d_year.isin([1998, 1999])
                 & dd.d_dom.between(1, 2)].d_date_sk
        sts = st[st.s_city.isin(["Midway", "Fairview"])].s_store_sk
        hds = hd[(hd.hd_dep_count == 4)
                 | (hd.hd_vehicle_count == 3)].hd_demo_sk
        j = (ss[ss.ss_sold_date_sk.isin(dds) & ss.ss_store_sk.isin(sts)
                & ss.ss_hdemo_sk.isin(hds)]
             .merge(ca[["ca_address_sk", "ca_city_id"]],
                    left_on="ss_addr_sk", right_on="ca_address_sk"))
        g = (j.groupby(["ss_ticket_number", "ss_customer_sk",
                        "ca_city_id"], dropna=False)
             .agg(extended_price=("ss_ext_sales_price",
                                  lambda s: s.sum(min_count=1)),
                  list_price=("ss_ext_list_price",
                              lambda s: s.sum(min_count=1)),
                  extended_tax=("ss_ext_tax",
                                lambda s: s.sum(min_count=1)))
             .reset_index())
        g = (g.merge(cu[["c_customer_sk", "c_current_addr_sk",
                         "c_first_name", "c_last_name"]],
                     left_on="ss_customer_sk", right_on="c_customer_sk")
             .merge(ca[["ca_address_sk", "ca_city_id"]]
                    .rename(columns={"ca_address_sk": "__cur_addr",
                                     "ca_city_id": "cur_city_id"}),
                    left_on="c_current_addr_sk", right_on="__cur_addr")
             .drop(columns=["c_customer_sk", "__cur_addr"]))
        g = g[g.cur_city_id != g.ca_city_id]
        g["city"] = [tpcds.CITIES[i - 1] for i in g.ca_city_id]
        g = (g.sort_values(["ss_customer_sk", "ss_ticket_number",
                            "ca_city_id"]).head(100))
        _assert_frame(got, g, float_cols=("extended_price", "list_price",
                                          "extended_tax"))

    def test_q79(self, data, pdf):
        got = QUERIES["q79"](data)
        ss, dd, st, hd = (pdf["store_sales"], pdf["date_dim"],
                          pdf["store"], pdf["household_demographics"])
        cu = pdf["customer"]
        dds = dd[(dd.d_dow == 1)
                 & dd.d_year.isin([1998, 1999])].d_date_sk
        hds = hd[(hd.hd_dep_count == 6)
                 | (hd.hd_vehicle_count > 2)].hd_demo_sk
        stf = st[st.s_number_employees.between(200, 295)]
        j = (ss[ss.ss_sold_date_sk.isin(dds) & ss.ss_hdemo_sk.isin(hds)]
             .merge(stf[["s_store_sk", "s_city_id"]],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        g = (j.groupby(["ss_ticket_number", "ss_customer_sk", "s_city_id"],
                       dropna=False)
             .agg(amt=("ss_coupon_amt", lambda s: s.sum(min_count=1)),
                  profit=("ss_net_profit", lambda s: s.sum(min_count=1)))
             .reset_index())
        g = (g.merge(cu[["c_customer_sk", "c_first_name", "c_last_name"]],
                     left_on="ss_customer_sk", right_on="c_customer_sk")
             .drop(columns=["c_customer_sk"]))
        g["city"] = [tpcds.CITIES[i - 1] for i in g.s_city_id]
        g = (g.sort_values(["ss_customer_sk", "ss_ticket_number",
                            "s_city_id"]).head(100))
        _assert_frame(got, g, float_cols=("amt", "profit"))


class TestBatchC:
    def test_q1(self, data, pdf):
        got = QUERIES["q1"](data)
        sr, dd, st, cu = (pdf["store_returns"], pdf["date_dim"],
                          pdf["store"], pdf["customer"])
        dds = dd[dd.d_year == 1998].d_date_sk
        ctr = (sr[sr.sr_returned_date_sk.isin(dds)]
               .groupby(["sr_customer_sk", "sr_store_sk"], dropna=False)
               ["sr_return_amt"].sum(min_count=1).reset_index()
               .rename(columns={"sr_return_amt": "ctr_total_return"}))
        avg = (ctr.groupby("sr_store_sk", dropna=False)
               ["ctr_total_return"].mean().reset_index()
               .rename(columns={"ctr_total_return": "avg_return"}))
        j = ctr.merge(avg, on="sr_store_sk")
        j = j[j.ctr_total_return > 1.2 * j.avg_return]
        sts = st[st.s_state == "TN"].s_store_sk
        j = j[j.sr_store_sk.isin(sts)]
        j = (j.merge(cu[["c_customer_sk", "c_customer_id"]],
                     left_on="sr_customer_sk", right_on="c_customer_sk")
             .drop(columns=["c_customer_sk"]))
        j = j.sort_values("sr_customer_sk").head(100)
        _assert_frame(got, j, float_cols=("ctr_total_return",
                                          "avg_return"))

    def test_q6(self, data, pdf):
        got = QUERIES["q6"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        cu, ca = pdf["customer"], pdf["customer_address"]
        cat_avg = (it.groupby("i_category_id")["i_current_price"]
                   .mean().rename("cat_avg"))
        it2 = it.merge(cat_avg, on="i_category_id")
        its = it2[it2.i_current_price > 1.2 * it2.cat_avg].i_item_sk
        dds = dd[(dd.d_year == 1998) & (dd.d_moy == 1)].d_date_sk
        j = (ss[ss.ss_sold_date_sk.isin(dds) & ss.ss_item_sk.isin(its)]
             .merge(cu[["c_customer_sk", "c_current_addr_sk"]],
                    left_on="ss_customer_sk", right_on="c_customer_sk")
             .merge(ca[["ca_address_sk", "ca_state_id"]],
                    left_on="c_current_addr_sk", right_on="ca_address_sk"))
        g = (j.groupby("ca_state_id").size().reset_index(name="cnt"))
        g = g[g.cnt >= 10]
        g["state"] = [tpcds.STATES[i - 1] for i in g.ca_state_id]
        g["cnt"] = g.cnt.astype("int64")
        g = g.sort_values(["cnt", "ca_state_id"]).head(100)
        _assert_frame(got, g)

    def _ratio_oracle(self, fact, it, date_lo, date_hi, cats, pfx):
        j = fact[(fact[f"{pfx}_sold_date_sk"] >= date_lo)
                 & (fact[f"{pfx}_sold_date_sk"] <= date_hi)]
        its = it[it.i_category_id.isin(cats)][["i_item_sk", "i_class_id"]]
        j = j.merge(its, left_on=f"{pfx}_item_sk", right_on="i_item_sk")
        g = (j.groupby(["i_class_id", f"{pfx}_item_sk"], dropna=False)
             [f"{pfx}_ext_sales_price"].sum(min_count=1).reset_index()
             .rename(columns={f"{pfx}_ext_sales_price": "itemrevenue"}))
        g["classrevenue"] = g.groupby("i_class_id")["itemrevenue"] \
            .transform(lambda s: s.sum(min_count=1))
        g["revenueratio"] = g.itemrevenue * 100.0 / g.classrevenue
        g["i_class"] = [tpcds.CLASSES[i - 1] for i in g.i_class_id]
        return (g.sort_values(["i_class_id", f"{pfx}_item_sk"])
                .head(100))

    def test_q12(self, data, pdf):
        got = QUERIES["q12"](data)
        want = self._ratio_oracle(pdf["web_sales"], pdf["item"],
                                  tpcds.DATE_SK0 + 280,
                                  tpcds.DATE_SK0 + 310, [1, 2, 3], "ws")
        _assert_frame(got, want, float_cols=("itemrevenue",
                                             "classrevenue",
                                             "revenueratio"))

    def test_q98(self, data, pdf):
        got = QUERIES["q98"](data)
        want = self._ratio_oracle(pdf["store_sales"], pdf["item"],
                                  tpcds.DATE_SK0 + 100,
                                  tpcds.DATE_SK0 + 130, [4, 5, 6], "ss")
        _assert_frame(got, want, float_cols=("itemrevenue",
                                             "classrevenue",
                                             "revenueratio"))

    def test_q67(self, data, pdf):
        got = QUERIES["q67"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        dts = dd[dd.d_year == 1999][["d_date_sk", "d_moy"]]
        j = (ss.merge(dts, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[["i_item_sk", "i_category_id"]],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        j["sales"] = j.ss_sales_price * j.ss_quantity
        g = (j.groupby(["i_category_id", "ss_store_sk", "d_moy"],
                       dropna=False)["sales"].sum(min_count=1)
             .reset_index().rename(columns={"sales": "sumsales"}))
        g["rk"] = (g.groupby("i_category_id", dropna=False)["sumsales"]
                   .rank(method="min", ascending=False, na_option="bottom")
                   .astype("int64"))
        g = g[g.rk <= 10]
        g["i_category"] = [tpcds.CATEGORIES[i - 1] for i in g.i_category_id]
        g = (g.sort_values(["i_category_id", "rk", "ss_store_sk",
                            "d_moy"]).head(100))
        _assert_frame(got, g, float_cols=("sumsales",))

    def test_q89(self, data, pdf):
        got = QUERIES["q89"](data)
        ss, dd, it = pdf["store_sales"], pdf["date_dim"], pdf["item"]
        dts = dd[dd.d_year == 1999][["d_date_sk", "d_moy"]]
        its = it[it.i_category_id.isin([1, 4, 7])][
            ["i_item_sk", "i_category_id", "i_class_id"]]
        j = (ss.merge(dts, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(its, left_on="ss_item_sk", right_on="i_item_sk"))
        g = (j.groupby(["i_category_id", "i_class_id", "ss_store_sk",
                        "d_moy"], dropna=False)["ss_sales_price"]
             .sum(min_count=1).reset_index()
             .rename(columns={"ss_sales_price": "sum_sales"}))
        part = ["i_category_id", "i_class_id", "ss_store_sk"]
        g["__part_sum"] = g.groupby(part, dropna=False)["sum_sales"] \
            .transform(lambda s: s.sum(min_count=1))
        g["__part_cnt"] = g.groupby(part, dropna=False)["sum_sales"] \
            .transform("count").astype("int64")
        g["avg_monthly_sales"] = g["__part_sum"] / g["__part_cnt"]
        g = g[(g.sum_sales - g.avg_monthly_sales).abs()
              > g.avg_monthly_sales * 0.1]
        g = g.copy()
        g["dev"] = g.sum_sales - g.avg_monthly_sales
        g = (g.sort_values(["dev", "ss_store_sk", "i_category_id",
                            "i_class_id", "d_moy"]).head(100))
        _assert_frame(got, g, float_cols=("sum_sales", "__part_sum",
                                          "avg_monthly_sales", "dev"))

    def test_q95(self, data, pdf):
        got = QUERIES["q95"](data)
        ws, wr, ca, web = (pdf["web_sales"], pdf["web_returns"],
                           pdf["customer_address"], pdf["web_site"])
        multi = (ws.groupby("ws_order_number")["ws_warehouse_sk"]
                 .nunique())
        multi = set(multi[multi > 1].index)
        cas = ca[ca.ca_state == "CA"].ca_address_sk
        webs = web[web.web_company_name == "pri"].web_site_sk
        lo, hi = tpcds.DATE_SK0 + 31, tpcds.DATE_SK0 + 91
        j = ws[(ws.ws_ship_date_sk >= lo) & (ws.ws_ship_date_sk <= hi)
               & ws.ws_bill_addr_sk.isin(cas)
               & ws.ws_web_site_sk.isin(webs)
               & ws.ws_order_number.isin(set(wr.wr_order_number))
               & ws.ws_order_number.isin(multi)]
        g = got.to_pydict()
        assert g["order_count"][0] == j.ws_order_number.nunique()
        np.testing.assert_allclose(g["ship_cost"][0],
                                   j.ws_ext_ship_cost.sum(), rtol=1e-9)
        np.testing.assert_allclose(g["net_profit"][0],
                                   j.ws_net_profit.sum(), rtol=1e-9)
