"""Test harness configuration.

Tests run on CPU with 8 virtual devices so the distributed layer (mesh
sharding, all_to_all shuffle) is exercised without TPU hardware — the
fake-backend capability the reference lacks (it gates tests on physical GPUs,
SURVEY.md §4).  Real-TPU runs use the same tests via ci/premerge-build.sh.
"""

import os

# Must happen before jax import.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = f"{_flags} --xla_force_host_platform_device_count=8".strip()
# Force CPU for tests even when the session points at a TPU (JAX_PLATFORMS=axon):
# the suite needs 8 virtual devices for mesh tests. Override with SRT_TEST_PLATFORM
# to run the suite on real hardware (ci/premerge-build.sh does). The env var alone
# is not enough — the TPU sitecustomize overrides jax.config directly, so we
# override it back (config wins over env at backend init).
_platform = os.environ.get("SRT_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(20260729)
