"""Pallas on-device RLE/bit-packed run expansion (encoded execution).

PR 11's native parquet scan crosses the host boundary with pages still
encoded and expands the merged run table on device
(`io.parquet_native._expand_runs`).  This kernel stages the identical
exact-integer expansion — per-output searchsorted run lookup, two-u32
word loads, per-run-width shift/mask — through Pallas, tiled over output
positions with the run table and word image resident in VMEM, so the
expansion never round-trips gather intermediates through HBM.

The arithmetic is copied expression-for-expression from the oracle: all
integer ops, so interpret mode (CPU) and TPU are bit-identical to the
jnp path by construction.  ``predicate_on_runs`` additionally evaluates
an equality predicate directly on the run table — sound only when every
run is RLE (the value never needs bit-unpacking); mixed tables fall back
to expand-then-compare.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: Output positions per grid step (the run table + word image ride along
#: whole; output lengths are pow2-padded by the caller, so this divides).
_TILE = 1024


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def expand_runs(words: jax.Array, out_start: jax.Array,
                rle_value: jax.Array, bp_bit_base: jax.Array,
                is_rle: jax.Array, width: jax.Array, *, n: int,
                interpret: bool = False) -> jax.Array:
    """Drop-in for ``io.parquet_native._expand_runs`` (same operands,
    same ``n`` int32 output)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = words.shape[0]
    T = min(n, _TILE)

    def kernel(words_ref, start_ref, rle_ref, base_ref, isrle_ref,
               width_ref, out_ref):
        j = pl.program_id(1)
        wimg = words_ref[...][0]
        out_start_v = start_ref[...][0]
        rle_value_v = rle_ref[...][0]
        bp_bit_base_v = base_ref[...][0]
        is_rle_v = isrle_ref[...][0]
        width_v = width_ref[...][0]
        # From here down: the oracle's expressions, verbatim.
        idx = (j * T + jnp.arange(T, dtype=jnp.int32)).astype(jnp.int32)
        run = jnp.searchsorted(out_start_v, idx,
                               side="right").astype(jnp.int32) - 1
        w = width_v[run]
        base = bp_bit_base_v[run] + \
            (idx - out_start_v[run]).astype(bp_bit_base_v.dtype) * \
            w.astype(bp_bit_base_v.dtype)
        word_idx = jnp.minimum((base >> 5).astype(jnp.int32), nw - 2)
        shift = (base & 31).astype(jnp.uint32)
        w0 = wimg[word_idx]
        w1 = wimg[word_idx + 1]
        packed = (w0 >> shift) | ((w1 << (31 - shift)) << 1)
        wmask = jnp.where(
            w >= 32, jnp.uint32(0xFFFFFFFF),
            (jnp.uint32(1) << jnp.clip(w, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1))
        packed = packed & wmask
        out_ref[0, :] = jnp.where(is_rle_v[run], rle_value_v[run],
                                  packed.astype(jnp.int32))

    nr = out_start.shape[0]
    grid = (1, n // T)    # singleton first dim: Mosaic x64 idiom
    ride = lambda m: pl.BlockSpec((1, m), lambda i, j: (i, i),
                                  memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        grid=grid,
        in_specs=[ride(nw), ride(nr), ride(nr), ride(nr), ride(nr),
                  ride(nr)],
        out_specs=pl.BlockSpec((1, T), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words[None, :], out_start[None, :], rle_value[None, :],
      bp_bit_base[None, :], is_rle[None, :], width[None, :])
    return out[0]


def predicate_on_runs(words: jax.Array, out_start: jax.Array,
                      rle_value: jax.Array, bp_bit_base: jax.Array,
                      is_rle: jax.Array, width: jax.Array, *, n: int,
                      value: int, interpret: bool = False) -> jax.Array:
    """``decoded == value`` without decoding, when sound.

    When every run is RLE the per-position value is just its run's RLE
    payload, so the predicate evaluates once per RUN and expands as a
    boolean gather — no bit-unpacking at all.  Any bit-packed run makes
    that unsound; those tables expand first and compare after
    (bit-identical either way, asserted in tests)."""
    if bool(jax.device_get(jnp.all(is_rle))):
        idx = jnp.arange(n, dtype=jnp.int32)
        run = jnp.searchsorted(out_start, idx,
                               side="right").astype(jnp.int32) - 1
        return rle_value[run] == jnp.int32(value)
    vals = expand_runs(words, out_start, rle_value, bp_bit_base, is_rle,
                       width, n=n, interpret=interpret)
    return vals == jnp.int32(value)
