"""Semantic subplan cache — cross-ticket common-subexpression
elimination for the serving layer (``SRT_SEMANTIC_CACHE``).

The workload miner (obs/workload.py) already *names* recurring subplan
prefixes (``materialize_subplan:<fp>`` recommendations); this module
closes the loop by actually materializing them.  At submission time the
scheduler's run-mode thunk enters :func:`run_table_plan` instead of
``run_plan`` directly:

  * the optimized plan's leading Filter/Project/Join chain is
    canonicalized exactly like the miner does —
    ``exec.optimize.prefix_step_texts`` hashed through
    ``obs.history.subplan_fingerprint`` — and keyed together with the
    submission's input identity (``serve.result_cache.input_digest``),
    so two *different* queries over the same input that share a prefix
    share one cache entry;
  * on a hit, the shared prefix is **not recomputed**: the plan is
    spliced (``exec.optimize.splice_prefix``) so a ``CachedSourceStep``
    leaf stands in for the prefix, and the executor resolves it to the
    materialized Table (``exec.compile.set_cached_source_resolver``)
    before binding, splitting, or metering — split-retry rungs operate
    on the resolved input and can never double-count it;
  * on a miss, interest is tallied per key; the *second* submission
    wanting the same prefix (or the first, when the workload advisor
    has **confirmed** the prefix) materializes it once under a
    non-blocking single-flight claim — a concurrent loser simply runs
    its full plan, so there is no cross-ticket blocking and no
    deadlock surface;
  * entries live in a byte-capped LRU whose eviction is hit-rate aware
    (fewest hits evict first, recency breaks ties), whose bytes are
    claimed against the admission controller's HBM budget
    (``AdmissionController.claim_cache`` — denied claims skip caching,
    never block), and whose *outcomes* feed back into the advisor:
    a cold eviction (zero hits) damps future ``materialize_subplan``
    recommendations for that prefix
    (``obs.workload.feed_semantic``).

Entries are pinned for the duration of any ticket holding a splice
into them, so eviction can never invalidate a running query.  Off
(``SRT_SEMANTIC_CACHE=0``, the default) this module is a transparent
pass-through to ``run_plan`` — the bit-identity oracle.

jax-free at module load, like the rest of the serving layer.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..config import (semantic_cache_bytes, semantic_cache_enabled,
                      views_auto, views_enabled)
from .result_cache import contains_deleted, input_digest, result_nbytes

#: A prefix must be wanted by this many submissions before it is
#: materialized (1 for advisor-confirmed prefixes — the policy loop's
#: fast path).
MATERIALIZE_MIN_INTEREST = 2

#: Bound on the interest / auto-candidate side tables.
_MAX_TRACKED = 4096


class _Entry:
    __slots__ = ("key", "prefix_fp", "value", "nbytes", "hits", "pins")

    def __init__(self, key: str, prefix_fp: str, value: Any, nbytes: int):
        self.key = key
        self.prefix_fp = prefix_fp
        self.value = value
        self.nbytes = nbytes
        self.hits = 0
        self.pins = 0


class SemanticCache:
    """Byte-capped, hit-rate-aware LRU of materialized subplan prefixes.

    Keys are ``<subplan_fingerprint>/<input_digest>``.  Unlike the
    result cache's oldest-first LRU, eviction prefers entries with the
    fewest hits (recency breaks ties) — a materialization that never
    paid for itself goes first, and its cold eviction is reported to
    the workload advisor.  Pinned entries (a ticket holds a splice into
    them) are never evicted."""

    def __init__(self, cap_bytes: int, admission=None):
        self.cap_bytes = int(cap_bytes)
        self.admission = admission
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hit_count = 0
        self.miss_count = 0
        self.materialize_count = 0
        self.evict_count = 0

    def get(self, key: str) -> Optional[_Entry]:
        """Counting lookup: a present entry is a hit (bumps its score
        and recency), an absent one is NOT counted here — the caller
        counts one miss per submission, not per probed depth."""
        from ..obs.metrics import counter
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            entry.hits += 1
            self._entries.move_to_end(key)
            self.hit_count += 1
        counter("serve.semantic.hit").inc()
        from ..obs import workload
        workload.feed_semantic("hit", entry.prefix_fp)
        return entry

    def peek(self, key: str) -> Optional[Any]:
        """Uncounted value lookup — the executor's CachedSourceStep
        resolver (the hit was already counted at splice time)."""
        with self._lock:
            entry = self._entries.get(key)
            return None if entry is None else entry.value

    def note_miss(self) -> None:
        from ..obs.metrics import counter
        with self._lock:
            self.miss_count += 1
        counter("serve.semantic.miss").inc()
        from ..obs import workload
        workload.feed_semantic("miss")

    def pin(self, key: str) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.pins += 1

    def unpin(self, key: str) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.pins > 0:
                entry.pins -= 1

    def put(self, key: str, prefix_fp: str, value: Any) -> bool:
        """Store a materialized prefix; False when it cannot be cached
        (buffers already donated away, unmeasurable, larger than the
        cap, or denied an HBM claim by the admission controller)."""
        payload = value[0] if isinstance(value, tuple) else value
        if contains_deleted(payload):
            from ..obs.metrics import counter
            counter("serve.cache.refused_deleted").inc()
            return False
        nbytes = result_nbytes(payload)
        if nbytes <= 0 or nbytes > self.cap_bytes:
            return False
        if self.admission is not None \
                and not self.admission.claim_cache(f"semantic:{key}", nbytes):
            return False
        from ..obs.metrics import counter, gauge
        evicted: List[_Entry] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                evicted.append(old)
            self._entries[key] = _Entry(key, prefix_fp, value, nbytes)
            self._bytes += nbytes
            self.materialize_count += 1
            evicted.extend(self._evict_locked())
            gauge("serve.semantic.bytes").set(self._bytes)
        counter("serve.semantic.materialize").inc()
        for entry in evicted:
            self._report_evicted(entry)
        return True

    def _evict_locked(self) -> List[_Entry]:
        """Evict unpinned entries, fewest-hits / least-recent first,
        until under the cap.  Caller holds the lock."""
        if self._bytes <= self.cap_bytes:
            return []
        order = {k: i for i, k in enumerate(self._entries)}
        victims = sorted(
            (e for e in self._entries.values() if e.pins == 0),
            key=lambda e: (e.hits, order[e.key]))
        evicted: List[_Entry] = []
        for entry in victims:
            if self._bytes <= self.cap_bytes:
                break
            del self._entries[entry.key]
            self._bytes -= entry.nbytes
            self.evict_count += 1
            evicted.append(entry)
        return evicted

    def _report_evicted(self, entry: _Entry) -> None:
        from ..obs.metrics import counter
        counter("serve.semantic.evict").inc()
        if self.admission is not None:
            self.admission.release_cache(f"semantic:{entry.key}")
        from ..obs import workload
        workload.feed_semantic("evict", entry.prefix_fp, hits=entry.hits)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lookups = self.hit_count + self.miss_count
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "cap_bytes": self.cap_bytes,
                "hits": self.hit_count,
                "misses": self.miss_count,
                "hit_rate": round(self.hit_count / lookups, 4)
                if lookups else 0.0,
                "materializations": self.materialize_count,
                "evictions": self.evict_count,
            }

    def clear(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        if self.admission is not None:
            for entry in entries:
                self.admission.release_cache(f"semantic:{entry.key}")


# ---------------------------------------------------------------------------
# Module state (one cache per process, like the compile cache)
# ---------------------------------------------------------------------------

_STATE_LOCK = threading.Lock()
_CACHE: Optional[SemanticCache] = None
_INTEREST: Dict[str, int] = {}
_INFLIGHT: set = set()
_CONFIRMED: set = set()
_AUTO_CANDIDATES: "OrderedDict[str, Any]" = OrderedDict()


def _resolver(key: str):
    cache = _CACHE
    return None if cache is None else cache.peek(key)


def _ensure_cache(admission=None) -> SemanticCache:
    global _CACHE
    with _STATE_LOCK:
        if _CACHE is None:
            _CACHE = SemanticCache(semantic_cache_bytes(),
                                   admission=admission)
            from ..exec.compile import set_cached_source_resolver
            set_cached_source_resolver(_resolver)
        elif _CACHE.admission is None and admission is not None:
            _CACHE.admission = admission
        return _CACHE


def _note_interest(key: str) -> int:
    with _STATE_LOCK:
        if key not in _INTEREST and len(_INTEREST) >= _MAX_TRACKED:
            _INTEREST.pop(next(iter(_INTEREST)))
        _INTEREST[key] = _INTEREST.get(key, 0) + 1
        return _INTEREST[key]


def confirmed_fps() -> Tuple[str, ...]:
    """Prefix fingerprints the workload advisor has *confirmed* as
    materialization targets (hysteresis-stable recommendations routed
    here through ``obs.workload.set_confirmed_sink``)."""
    with _STATE_LOCK:
        return tuple(sorted(_CONFIRMED))


def _note_auto_candidate(opt) -> None:
    """Remember group-by-terminated plans by their prefix fingerprints,
    so a later advisor confirmation can auto-register them as
    materialized views (``SRT_VIEWS_AUTO``).  Structural check only —
    jax-free, fallible, never raises."""
    try:
        steps = getattr(opt, "steps", ())
        if not steps or type(steps[-1]).__name__ != "GroupAggStep" \
                or getattr(steps[-1], "sets", None) is not None:
            return
        from ..exec.optimize import prefix_step_texts, source_plan
        from ..obs.history import subplan_fingerprint
        src = source_plan(opt)
        with _STATE_LOCK:
            for texts in prefix_step_texts(opt):
                fp = subplan_fingerprint(texts)
                if fp not in _AUTO_CANDIDATES:
                    while len(_AUTO_CANDIDATES) >= _MAX_TRACKED:
                        _AUTO_CANDIDATES.popitem(last=False)
                    _AUTO_CANDIDATES[fp] = src
    except Exception:
        pass


def _on_confirmed(fps: List[str]) -> None:
    """The workload advisor's confirmed-recommendation sink: remember
    confirmed prefixes (they materialize on first interest) and — under
    ``SRT_VIEWS`` + ``SRT_VIEWS_AUTO`` — auto-register any known
    group-by-terminated plan over a confirmed prefix as a materialized
    view named ``auto:<fp>``."""
    with _STATE_LOCK:
        _CONFIRMED.update(fps)
        candidates = {fp: _AUTO_CANDIDATES[fp] for fp in fps
                      if fp in _AUTO_CANDIDATES}
    if not candidates or not views_enabled() or not views_auto():
        return
    from ..views import registry
    from ..obs import workload
    from ..obs.metrics import counter
    for fp, plan in candidates.items():
        name = f"auto:{fp}"
        if registry.get(name) is not None:
            continue
        try:
            registry.register(name, plan, auto=True)
        except Exception:
            continue
        counter("serve.semantic.auto_view").inc()
        workload.feed_semantic("auto_view", fp)


# The sink is installed at import: the advisor's confirmations reach
# the cache whether or not a query ran through it yet (workload is
# jax-free, so this costs nothing at import).
from ..obs import workload as _workload  # noqa: E402

_workload.set_confirmed_sink(_on_confirmed)


# ---------------------------------------------------------------------------
# The serving entry point
# ---------------------------------------------------------------------------

def run_table_plan(plan, table, admission=None):
    """``run_plan`` with cross-ticket prefix CSE — the serving
    scheduler's run-mode executor.  Bit-identical to
    ``run_plan(plan, table)``; with ``SRT_SEMANTIC_CACHE=0`` it *is*
    ``run_plan(plan, table)``."""
    from ..exec.compile import run_plan
    if not semantic_cache_enabled():
        return run_plan(plan, table)
    from ..exec.optimize import (optimize, prefix_plan, prefix_step_texts,
                                 splice_prefix)
    from ..obs.history import subplan_fingerprint
    opt = optimize(plan)
    if getattr(table, "num_rows", 0) <= 0:
        return run_plan(opt, table)
    nsteps = len(opt.steps)
    # Strict prefixes only, and only row-aligned ones: a shuffled join
    # replaces the row population (its expansion is not index-aligned
    # with the input), so its output cannot be cached in the
    # position-preserving form the bit-identity splice requires.
    chains = [texts for texts in prefix_step_texts(opt)
              if len(texts) < nsteps
              and not any(t.startswith("ShuffledJoin[") for t in texts)]
    if not chains:
        return run_plan(opt, table)
    digest = input_digest(table)
    if digest is None:
        return run_plan(opt, table)
    cache = _ensure_cache(admission)
    _note_auto_candidate(opt)
    keyed = sorted(((len(texts), subplan_fingerprint(texts))
                    for texts in chains), reverse=True)
    keyed = [(depth, fp, f"{fp}/{digest}") for depth, fp in keyed]

    for depth, fp, key in keyed:                       # deepest hit wins
        if cache.get(key) is None:
            continue
        cache.pin(key)
        try:
            return run_plan(splice_prefix(opt, depth, key), table)
        finally:
            cache.unpin(key)

    cache.note_miss()
    confirmed = confirmed_fps()
    target = None
    for depth, fp, key in keyed:                       # deepest eligible
        interest = _note_interest(key)
        threshold = 1 if fp in confirmed else MATERIALIZE_MIN_INTEREST
        if target is None and interest >= threshold:
            target = (depth, fp, key)
    if target is None:
        return run_plan(opt, table)

    depth, fp, key = target
    with _STATE_LOCK:                                  # single flight
        if key in _INFLIGHT:
            target = None
        else:
            _INFLIGHT.add(key)
    if target is None:                                 # lost the claim:
        return run_plan(opt, table)                    # full plan, no wait
    try:
        try:
            payload = _materialize_prefix(prefix_plan(opt, depth), table)
        except Exception:
            # The padded runner has no recovery ladder — an injected
            # fault (or OOM) aborts the materialization attempt and the
            # submission falls through to the full resilient run.
            payload = None
        if payload is None:
            return run_plan(opt, table)
        from ..obs import workload
        workload.feed_semantic("materialize", fp)
        stored = cache.put(key, fp, payload)
        if not stored:
            value, names, sel_name = payload
            return run_plan(_resume_plan(opt, depth, names, sel_name),
                            value)
        cache.pin(key)
        try:
            return run_plan(splice_prefix(opt, depth, key), table)
        finally:
            cache.unpin(key)
    finally:
        with _STATE_LOCK:
            _INFLIGHT.discard(key)


def _materialize_prefix(prefix, table):
    """Run ``prefix`` position-preserving and package the cacheable
    payload ``(value, names, sel_name)``: the prefix's output sliced
    back to the source's logical length (pad rows dropped, row
    positions untouched) with its live-row selection riding as an extra
    ``sel_name`` column.  The splice's resume steps
    (``exec.optimize.resume_prefix_steps``) re-enter the executor's
    ``(columns, selection)`` state from this payload, so downstream
    float accumulation happens over the same row positions as the fused
    run — compacting here instead would re-order the sums and drift the
    last ulp off the bit-identity oracle.

    None when the output cannot be re-bound positionally
    (variable-width or nested columns at the prefix boundary)."""
    from ..column import Column
    from ..exec.compile import run_plan_padded
    from ..table import Table
    t, sel_col = run_plan_padded(prefix, table)
    names = t.names
    for nm in names:
        c = t[nm]
        if c.offsets is not None or c.children or c.data is None:
            return None
    n = table.num_rows

    def _cut(c):
        if int(c.data.shape[0]) == n:
            return c
        return Column(data=c.data[:n],
                      validity=None if c.validity is None
                      else c.validity[:n],
                      dtype=c.dtype)

    value = Table([(nm, _cut(t[nm])) for nm in names])
    sel_name = None
    if sel_col is not None:
        sel_name = "__srt_sel__"
        while sel_name in names:
            sel_name += "_"
        value = value.with_column(sel_name, _cut(sel_col))
    return value, names, sel_name


def _resume_plan(opt, depth: int, names, sel_name):
    """``opt`` resuming after its first ``depth`` steps over an in-hand
    position-preserving prefix payload — the fallback when a freshly
    computed prefix could not be admitted to the cache.  The resume
    steps restore the (columns, selection) state exactly as the
    executor's CachedSourceStep resolver would."""
    from ..exec.optimize import resume_prefix_steps
    from ..exec.plan import Plan
    rest = Plan(resume_prefix_steps(tuple(names), sel_name)
                + tuple(opt.steps[depth:]))
    info = getattr(opt, "opt", None)
    if info is not None:
        object.__setattr__(rest, "opt", info)
    return rest


# ---------------------------------------------------------------------------
# Observability surfaces
# ---------------------------------------------------------------------------

def stats() -> Dict[str, Any]:
    """Semantic-cache stats for ``/views``, ``obs views``, and the
    semantic bench lane.  Well-defined before any query ran."""
    cache = _CACHE
    base: Dict[str, Any] = {
        "enabled": semantic_cache_enabled(),
        "entries": 0, "bytes": 0, "cap_bytes": 0,
        "hits": 0, "misses": 0, "hit_rate": 0.0,
        "materializations": 0, "evictions": 0,
    }
    if cache is not None:
        base.update(cache.stats())
        base["enabled"] = semantic_cache_enabled()
    base["confirmed_prefixes"] = list(confirmed_fps())
    return base


def bundle_block(plan=None) -> Dict[str, Any]:
    """Semantic block for a postmortem bundle: was the cache on, did
    this query use it (a resolved splice marks the plan), and — the
    doctor's hook — did the query recompute a prefix the workload
    advisor had already *confirmed* for materialization
    (``hot_prefix_recompute``)?  Never raises."""
    enabled = False
    try:
        enabled = semantic_cache_enabled()
    except Exception:
        pass
    used = plan is not None \
        and getattr(plan, "_cached_source_key", None) is not None
    fps: List[str] = []
    if plan is not None:
        try:
            from ..exec.optimize import prefix_step_texts
            from ..obs.history import subplan_fingerprint
            fps = [subplan_fingerprint(t) for t in prefix_step_texts(plan)]
        except Exception:
            fps = []
    confirmed = set(confirmed_fps())
    return {
        "enabled": bool(enabled),
        "used": bool(used),
        "prefix_fingerprints": fps,
        "hot_prefix_recompute": bool(
            enabled and not used and any(fp in confirmed for fp in fps)),
    }


def reset() -> None:
    """Drop the cache, interest, claims, and confirmations (test/bench
    isolation); releases every admission claim and uninstalls the
    executor resolver."""
    global _CACHE
    with _STATE_LOCK:
        cache, _CACHE = _CACHE, None
        _INTEREST.clear()
        _INFLIGHT.clear()
        _CONFIRMED.clear()
        _AUTO_CANDIDATES.clear()
    if cache is not None:
        cache.clear()
        import sys
        compile_mod = sys.modules.get("spark_rapids_tpu.exec.compile")
        if compile_mod is not None:
            compile_mod.set_cached_source_resolver(None)
