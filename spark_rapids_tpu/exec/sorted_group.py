"""Sync-free sort-based group-by for compiled plans (the general path).

The eager sort-based groupby (:mod:`..ops.groupby`) materializes the group
count on the host to produce exact-shaped outputs.  Inside a compiled plan
that sync is not available, so this kernel keeps everything padded at the
input length ``n`` and returns a live-group selection vector instead:

1. one stable multi-operand ``lax.sort`` clusters rows by key, with a
   leading selection rank so filtered-out rows sink to the end, and every
   needed payload (group keys for reconstruction, aggregation values, the
   hidden rowid) riding as extra operands — the same fused-sort shape the
   eager path measured fastest;
2. group boundaries come from adjacent-difference over the sorted key
   operands, masked to live rows;
3. per-group reductions are **inclusive segmented scans**
   (``lax.associative_scan`` restarting at boundaries) read off at each
   group's last row — no ``segment_sum`` scatters, which the TPU memory
   system punishes;
4. group start/end positions materialize as padded ``(n,)`` arrays via a
   value-sort of ``where(boundary, row, n)`` — ascending true starts
   first, ``n`` padding after — so outputs are plain gathers.

Slots past the true group count hold garbage and are dropped by the
returned selection; downstream plan steps (sort/limit) and
materialization handle them uniformly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..column import Column
from ..ops.common import (adjacent_differs, distinct_run_heads,
                          grouping_sort_operands)
from ..ops.groupby import _agg_out_dtype, _minmax_identity, _sum_dtype
from .plan import GroupAggStep


def _segmented_scan_multi(fields: dict[str, tuple[jax.Array, str]],
                          boundary: jax.Array) -> dict[str, jax.Array]:
    """ONE inclusive segmented scan serving all of a group-by's aggregates
    (the shared chunked implementation lives in ops.common — see
    chunked_segmented_scan for the compile-time story)."""
    from ..ops.common import chunked_segmented_scan
    return chunked_segmented_scan(fields, boundary)


def _nunique_padded(cols: dict[str, Column], sel, key_names,
                    value_name: str, ends=None) -> jax.Array:
    """Per-group distinct non-null value counts, padded to n, in group-rank
    order (sorted keys — aligned with the main kernel's output slots).

    Own ``lax.sort`` over (selection, keys..., value): a distinct-run head
    is a live, valid row whose (key, value) pair differs from its
    predecessor.  ``ends`` (per-group last rows) may be passed by a caller
    that already computed them — this sort's group segments provably match
    the main kernel's (same live rows and key operands; value operands
    only permute rows within key groups)."""
    n = next(iter(cols.values())).size
    iota = jnp.arange(n, dtype=jnp.int32)
    key_cols = [cols[k] for k in key_names]
    key_ops = grouping_sort_operands(
        tuple(c.data for c in key_cols),
        tuple(c.validity for c in key_cols))
    vcol = cols[value_name]
    val_ops = grouping_sort_operands((vcol.data,), (vcol.validity,))
    ops_list = list(key_ops) + list(val_ops)
    if sel is not None:
        ops_list = [jnp.where(sel, jnp.uint8(0), jnp.uint8(1))] + ops_list
    sorted_all = jax.lax.sort(ops_list, dimension=0, is_stable=False,
                              num_keys=len(ops_list))
    off = 1 if sel is not None else 0
    live = (sorted_all[0] == 0) if sel is not None else None
    key_boundary, head = distinct_run_heads(
        sorted_all[off:off + len(key_ops)],
        sorted_all[off + len(key_ops):], live=live)

    scans = _segmented_scan_multi(
        {"h": (head.astype(jnp.int64), "add")}, key_boundary)
    if ends is None:
        starts = jax.lax.sort(
            [jnp.where(key_boundary, iota, jnp.int32(n))], dimension=0,
            is_stable=False, num_keys=1)[0]
        ends = jnp.clip(jnp.concatenate(
            [starts[1:], jnp.array([n], jnp.int32)]) - 1, 0, n - 1)
    return jnp.take(scans["h"], ends)


def _median_padded(cols: dict[str, Column], sel, key_names,
                   value_name: str, ends) -> tuple[jax.Array, jax.Array]:
    """Per-group linear-interpolated median, padded to n, group-rank
    aligned (see _nunique_padded for why the side sort's segments match
    the caller's ``ends``).  Returns (float64 medians, validity)."""
    n = next(iter(cols.values())).size
    key_cols = [cols[k] for k in key_names]
    key_ops = grouping_sort_operands(
        tuple(c.data for c in key_cols),
        tuple(c.validity for c in key_cols))
    vcol = cols[value_name]
    val_ops = grouping_sort_operands((vcol.data,), (vcol.validity,))
    ops_list = list(key_ops) + list(val_ops)
    if sel is not None:
        ops_list = [jnp.where(sel, jnp.uint8(0), jnp.uint8(1))] + ops_list
    sorted_all = jax.lax.sort(ops_list + [vcol.data], dimension=0,
                              is_stable=False, num_keys=len(ops_list))
    off = 1 if sel is not None else 0
    live = (sorted_all[0] == 0) if sel is not None else jnp.ones(n, jnp.bool_)
    key_boundary = jnp.zeros(n, jnp.bool_)
    for op in sorted_all[off:off + len(key_ops)]:
        key_boundary = key_boundary | adjacent_differs(op)
    key_boundary = key_boundary & live
    valid_sorted = (sorted_all[off + len(key_ops)] == 1) & live
    svalues = sorted_all[-1]

    scans = _segmented_scan_multi(
        {"nl": ((live & ~valid_sorted).astype(jnp.int32), "add"),
         "vc": (valid_sorted.astype(jnp.int32), "add")}, key_boundary)
    nulls = jnp.take(scans["nl"], ends)
    vcount = jnp.take(scans["vc"], ends)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), ends[:-1] + 1])
    run0 = starts + nulls
    lo = jnp.clip(run0 + jnp.maximum(vcount - 1, 0) // 2, 0, n - 1)
    hi = jnp.clip(run0 + vcount // 2, 0, n - 1)
    med = (jnp.take(svalues, lo).astype(jnp.float64)
           + jnp.take(svalues, hi).astype(jnp.float64)) / 2.0
    if vcol.dtype.is_decimal:
        med = med * (10.0 ** vcol.dtype.scale)
    return med, vcount > 0


def sorted_group_agg(cols: dict[str, Column], sel, step: GroupAggStep):
    n = next(iter(cols.values())).size
    iota = jnp.arange(n, dtype=jnp.int32)

    key_cols = [cols[k] for k in step.keys]
    key_ops = grouping_sort_operands(
        tuple(c.data for c in key_cols),
        tuple(c.validity for c in key_cols))
    ops_list = list(key_ops)
    if sel is not None:
        ops_list = [jnp.where(sel, jnp.uint8(0), jnp.uint8(1))] + ops_list

    # Payload columns: keys (for output reconstruction) + distinct agg
    # value columns. Each contributes data (+ validity when present).
    pay_names: list[str] = []
    for k in step.keys:
        pay_names.append(k)
    main_pay = {vn for vn, how, _ in step.aggs
                if how not in ("nunique", "median")}
    for value_name, how, _ in step.aggs:
        # nunique/median re-sort their value column in their own kernels
        if value_name not in pay_names and value_name in main_pay:
            pay_names.append(value_name)
    payload: list[jax.Array] = []
    layout: list[bool] = []
    for nm in pay_names:
        c = cols[nm]
        payload.append(c.data)
        has_v = c.validity is not None
        if has_v:
            payload.append(c.validity)
        layout.append(has_v)

    sorted_all = jax.lax.sort(ops_list + payload, dimension=0,
                              is_stable=True, num_keys=len(ops_list))
    live = (sorted_all[0] == 0) if sel is not None else jnp.ones(n, jnp.bool_)
    sorted_keys = sorted_all[(1 if sel is not None else 0):len(ops_list)]
    rest = list(sorted_all[len(ops_list):])
    sorted_cols: dict[str, Column] = {}
    i = 0
    for nm, has_v in zip(pay_names, layout):
        d = rest[i]; i += 1
        v = None
        if has_v:
            v = rest[i]; i += 1
        sorted_cols[nm] = Column(data=d, validity=v, dtype=cols[nm].dtype)

    boundary = jnp.zeros(n, jnp.bool_)
    for op_arr in sorted_keys:
        boundary = boundary | adjacent_differs(op_arr)
    boundary = boundary & live

    num_groups = jnp.sum(boundary.astype(jnp.int32))
    sel_out = iota < num_groups

    # Padded per-group start rows (ascending true starts, then n-padding),
    # then end rows; scans read at ends are exact because dead rows carry
    # reduction identities.
    starts = jax.lax.sort(
        [jnp.where(boundary, iota, jnp.int32(n))], dimension=0,
        is_stable=False, num_keys=1)[0]
    ends = jnp.concatenate([starts[1:], jnp.array([n], jnp.int32)]) - 1
    ends = jnp.clip(ends, 0, n - 1)
    g_starts = jnp.clip(starts, 0, n - 1)

    # Collect every needed per-group reduction as a field of ONE segmented
    # scan (see _segmented_scan_multi).
    fields: dict[str, tuple[jax.Array, str]] = {}

    def lives(nm: str) -> jax.Array:
        c = sorted_cols[nm]
        return live if c.validity is None else (live & c.validity)

    need_last = False
    for value_name, how, _ in step.aggs:
        if how in ("nunique", "median"):
            continue
        c = sorted_cols[value_name]
        if how == "count_all" and "ca" not in fields:
            fields["ca"] = (live.astype(jnp.int64), "add")
        elif how == "count":
            fields.setdefault("cnt:" + value_name,
                              (lives(value_name).astype(jnp.int64), "add"))
        elif how == "last":
            need_last = True
        elif how == "first":
            pass
        elif how in ("sum", "mean", "var", "std"):
            acc = _sum_dtype(c.dtype)
            ok = lives(value_name)
            v = jnp.where(ok, c.data,
                          jnp.zeros((), c.data.dtype)).astype(acc.jnp_dtype)
            fields.setdefault("sum:" + value_name, (v, "add"))
            fields.setdefault("cnt:" + value_name,
                              (ok.astype(jnp.int64), "add"))
            if how in ("var", "std"):
                fv = jnp.where(ok, c.data, jnp.zeros((), c.data.dtype)
                               ).astype(jnp.float64)
                fields.setdefault("sumsq:" + value_name, (fv * fv, "add"))
        else:                                  # min / max
            ident = _minmax_identity(c.dtype, how == "min")
            ok = lives(value_name)
            fields.setdefault(
                how + ":" + value_name,
                (jnp.where(ok, c.data, ident), how))
            fields.setdefault("cnt:" + value_name,
                              (ok.astype(jnp.int64), "add"))
    if need_last:
        fields["lastlive"] = (jnp.where(live, iota, jnp.int32(-1)), "max")

    scans = (_segmented_scan_multi(fields, boundary) if fields else {})
    at_ends = {k: jnp.take(v, ends) for k, v in scans.items()}
    last_pos = (jnp.clip(at_ends["lastlive"], 0, n - 1) if need_last
                else None)

    out: dict[str, Column] = {}
    for km_name in step.keys:
        c = sorted_cols[km_name]
        out[km_name] = Column(
            data=jnp.take(c.data, g_starts),
            validity=None if c.validity is None
            else jnp.take(c.validity, g_starts),
            dtype=c.dtype)

    nunique_cache: dict[str, jax.Array] = {}
    median_cache: dict[str, tuple] = {}
    for value_name, how, out_name in step.aggs:
        if how == "nunique":
            if value_name not in nunique_cache:
                nunique_cache[value_name] = _nunique_padded(
                    cols, sel, step.keys, value_name, ends=ends)
            out[out_name] = Column(data=nunique_cache[value_name],
                                   dtype=_agg_out_dtype(None, "nunique"))
            continue
        if how == "median":
            if value_name not in median_cache:
                median_cache[value_name] = _median_padded(
                    cols, sel, step.keys, value_name, ends=ends)
            med, ok = median_cache[value_name]
            out[out_name] = Column(data=med, validity=ok,
                                   dtype=_agg_out_dtype(None, "median"))
            continue
        c = sorted_cols[value_name]
        dtype = c.dtype
        out_dtype = _agg_out_dtype(dtype, how)
        has_valid = None
        if how == "count_all":
            data = at_ends["ca"]
        elif how == "count":
            data = at_ends["cnt:" + value_name]
        elif how == "first":
            data = jnp.take(c.data, g_starts)
            has_valid = (None if c.validity is None
                         else jnp.take(c.validity, g_starts))
        elif how == "last":
            data = jnp.take(c.data, last_pos)
            has_valid = (None if c.validity is None
                         else jnp.take(c.validity, last_pos))
        elif how == "sum":
            data = at_ends["sum:" + value_name]
            has_valid = at_ends["cnt:" + value_name] > 0
        elif how in ("mean", "var", "std"):
            scale_factor = 10.0 ** dtype.scale if dtype.is_decimal else 1.0
            fsums = at_ends["sum:" + value_name].astype(
                jnp.float64) * scale_factor
            fcounts = at_ends["cnt:" + value_name].astype(jnp.float64)
            if how == "mean":
                data = fsums / jnp.maximum(fcounts, 1.0)
                has_valid = at_ends["cnt:" + value_name] > 0
            else:
                sumsq = at_ends["sumsq:" + value_name] * (scale_factor
                                                          * scale_factor)
                denom = jnp.maximum(fcounts - 1.0, 1.0)
                var = (sumsq - fsums * fsums
                       / jnp.maximum(fcounts, 1.0)) / denom
                var = jnp.maximum(var, 0.0)
                data = var if how == "var" else jnp.sqrt(var)
                has_valid = at_ends["cnt:" + value_name] > 1
        else:                                  # min / max
            data = at_ends[how + ":" + value_name]
            has_valid = at_ends["cnt:" + value_name] > 0
        out[out_name] = Column(data=data.astype(out_dtype.jnp_dtype),
                               validity=has_valid, dtype=out_dtype)

    return out, sel_out
