"""Execution-resilience layer: classification, bounded retry, HBM-OOM
recovery, and deterministic fault injection.

The reference engine earns production trust by surviving device OOM and
transient IO failure — Spark retries tasks, the RAPIDS plugin falls back
or splits its input batches under GPU memory pressure.  This package is
that layer for the TPU engine, threaded through bind/compile/dispatch/
materialize (exec/compile.py) and the streaming executor (exec/stream.py):

  * :func:`classify` — ONE mapping from raised exceptions to retryable
    categories: ``"oom"`` (``XlaRuntimeError``/``RESOURCE_EXHAUSTED``),
    ``"compile"`` (XLA compilation failures), ``"io"`` (transient
    reader/network errors), ``"fatal"`` (everything else — never retried).
  * :func:`with_retries` — bounded retry with capped exponential backoff
    (``SRT_RETRY_MAX``, ``SRT_RETRY_BACKOFF``); on budget exhaustion the
    ORIGINAL error re-raises with a :class:`RecoverySummary` attached.
  * the HBM-OOM recovery ladder (:mod:`.recovery`): evict the whole-plan
    compile cache + bucket pad cache and retry; if the OOM recurs, split
    the batch in half along rows (snapped to the bucket schedule) and
    re-run the pieces; only then fail — raising
    :class:`ExecutionRecoveryError` chained to the original error and
    naming every step attempted.
  * :func:`fault_point` — deterministic fault injection via ``SRT_FAULT``
    (e.g. ``oom:materialize:2``, ``io:read:0.5:seed=7``,
    ``oom:dist-dispatch:1:shard=3``) so every recovery path above —
    including shard-local mesh failures — runs on CPU in tier-1 CI.
  * the MESH ladder (exec/dist.py, built on :func:`.recovery.oom_ladder`
    with ``dist=True``): evict → retry → per-shard split → (opt-in via
    ``SRT_DIST_FALLBACK=collect``) collect the DistTable and finish the
    plan single-chip — a degraded-but-correct answer, recorded as a
    named rung.  :func:`dist_guard` (``SRT_DIST_TIMEOUT``) bounds mesh
    collectives/``collect()`` with a stall watchdog raising
    :class:`DistStallError` instead of hanging the host.

Recovery is observable: :func:`recovery_stats` accumulates retries /
splits / cache evictions / backoff seconds, surfaced as the ``recovery``
block of QueryMetrics (obs/query.py, schema_version 3) and the
benchmarks' ``recovery`` JSON line.

This package must not import jax at module load (the lazy-import rule of
config.py): classification is string/type-name based and injection is
pure python, so failure-model tooling runs on hosts without the XLA
stack.  jax loads only inside :mod:`.recovery` at recovery time — by
which point the engine (and therefore jax) is necessarily live.
"""

from .classify import (CATEGORY_COMPILE, CATEGORY_FATAL, CATEGORY_IO,
                       CATEGORY_OOM, DistStallError, ExecutionRecoveryError,
                       RecoverySummary, ShuffleOverflowError,
                       StreamStallError, classify)
from .faults import InjectedFault, fault_point, reset_faults
from .retry import (RecoveryStats, RetryPolicy, recovery_stats, with_retries)
from .spill import (SpillManager, maybe_proactive_spill, reset_spill,
                    spill_manager)
from .watchdog import dist_guard

__all__ = [
    "CATEGORY_COMPILE", "CATEGORY_FATAL", "CATEGORY_IO", "CATEGORY_OOM",
    "DistStallError", "ExecutionRecoveryError", "InjectedFault",
    "RecoveryStats", "RecoverySummary", "RetryPolicy", "ShuffleOverflowError",
    "SpillManager", "StreamStallError", "classify", "dist_guard",
    "fault_point", "maybe_proactive_spill", "recovery_stats", "reset_faults",
    "reset_spill", "spill_manager", "with_retries",
]
