"""Pallas dense group-by accumulate.

``exec.compile._dense_accumulate`` folds row chunks into per-cell
accumulators with ``jax.lax.scan(body, init, xs)``; XLA materializes the
one-hot / masked intermediates of every chunk step in HBM.  This kernel
runs the SAME ``body`` inside one Pallas program: the accumulator dict
lives in a VMEM output block revisited across a sequential grid over
chunks, so each (cells × chunk) intermediate exists only inside one grid
step.

Bit-identity is by construction — the caller's own ``body`` closure runs
on each chunk in the same order with the same float op order, so the
fold is the oracle fold, just staged through Pallas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dense_accumulate(xs: dict, init: dict, body, *,
                     interpret: bool = False) -> dict:
    """Drop-in for ``jax.lax.scan(body, init, xs)[0]`` over chunked
    column dicts: ``xs`` leaves are ``(nchunks, B)``, ``init`` leaves
    ``(cells,)``, ``body(acc, chunk) -> (acc, None)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xs_keys = sorted(xs)
    acc_keys = sorted(init)
    nchunks, B = xs[xs_keys[0]].shape
    if nchunks == 0:
        return dict(init)
    G = init[acc_keys[0]].shape[0]

    # Pallas kernels cannot capture array constants from the caller's
    # closure (the cell-id iota, agg identities, ...) — trace the body
    # to a jaxpr once and feed its constants in as ride-along inputs.
    chunk0 = {k: jax.ShapeDtypeStruct((B,), xs[k].dtype) for k in xs_keys}
    acc0 = {k: jax.ShapeDtypeStruct((G,), init[k].dtype) for k in acc_keys}
    fold = lambda a, c: body(a, c)[0]
    closed = jax.make_jaxpr(fold)(acc0, chunk0)
    out_tree = jax.tree_util.tree_structure(jax.eval_shape(fold, acc0,
                                                           chunk0))
    consts = [jnp.asarray(c) for c in closed.consts]
    const_shapes = [tuple(c.shape) for c in consts]

    def pure_body(acc, chunk, *cvals):
        flat_in, _ = jax.tree_util.tree_flatten((acc, chunk))
        out_flat = jax.core.eval_jaxpr(closed.jaxpr, list(cvals), *flat_in)
        return jax.tree_util.tree_unflatten(out_tree, out_flat)

    def kernel(*refs):
        nacc, nxs, nc = len(acc_keys), len(xs_keys), len(consts)
        init_refs = refs[:nacc]
        xs_refs = refs[nacc:nacc + nxs]
        const_refs = refs[nacc + nxs:nacc + nxs + nc]
        out_refs = refs[nacc + nxs + nc:]
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _seed():
            for oref, iref in zip(out_refs, init_refs):
                oref[...] = iref[...]

        acc = {k: oref[0, :] for k, oref in zip(acc_keys, out_refs)}
        chunk = {k: xref[0, :] for k, xref in zip(xs_keys, xs_refs)}
        cvals = [ref[...].reshape(s)
                 for ref, s in zip(const_refs, const_shapes)]
        out = pure_body(acc, chunk, *cvals)
        for k, oref in zip(acc_keys, out_refs):
            oref[0, :] = out[k]

    # Singleton-first-dim grid; accumulator blocks revisit (index maps
    # built from program ids only — the Mosaic x64 idiom of rows/image).
    grid = (1, nchunks)
    acc_spec = pl.BlockSpec((1, G), lambda i, j: (i, i),
                            memory_space=pltpu.VMEM)
    ride = lambda m: pl.BlockSpec((1, m), lambda i, j: (i, i),
                                  memory_space=pltpu.VMEM)
    in_specs = ([acc_spec for _ in acc_keys] +
                # xs leaves are (nchunks, B): the CHUNK axis is axis 0,
                # so the advancing grid coordinate lands first.
                [pl.BlockSpec((1, B), lambda i, j: (j, i),
                              memory_space=pltpu.VMEM) for _ in xs_keys] +
                [ride(max(1, int(np_prod(s)))) for s in const_shapes])
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((1, G), init[k].dtype)
                        for k in acc_keys),
        grid=grid,
        in_specs=in_specs,
        out_specs=tuple(acc_spec for _ in acc_keys),
        interpret=interpret,
    )(*[init[k][None, :] for k in acc_keys],
      *[xs[k] for k in xs_keys],
      *[c.reshape(1, -1) if c.ndim else c.reshape(1, 1) for c in consts])
    return {k: o[0] for k, o in zip(acc_keys, outs)}


def np_prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out
