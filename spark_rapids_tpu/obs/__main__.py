"""``python -m spark_rapids_tpu.obs`` — console tooling over obs state.

``top``
    htop-style live query view: polls the in-process live registry
    (obs/live.py) or, with ``--url``, a remote exporter's ``/queries``
    endpoint (obs/server.py) and redraws a console table of in-flight
    queries: phase, batches done / in-flight, rows/sec, ICI bytes, last
    recovery rung, and one progress bar per shard.  ``--once`` prints a
    single frame (scripts, CI, docs); default is a 1 Hz refresh until
    Ctrl-C.
``doctor <bundle.json | fingerprint>``
    postmortem analysis (obs/doctor.py): rank what failed or got slow
    in one bundle — or a plan fingerprint's newest history record —
    against the same-fingerprint history baseline, and print the
    verdict.  Exits 0 whenever a verdict was produced.
``advisor``
    one capacity-advisor evaluation (obs/capacity.py): the saturation
    snapshot plus ranked, evidence-cited recommendations.  Reads the
    local in-process window by default, a remote exporter's
    ``/capacity`` with ``--url``, or — with ``--history`` — replays a
    metrics-history JSONL offline (newest ``--last`` records via the
    tail-seeking reverse reader).  Exits 0 whenever a verdict was
    produced.
``workload``
    one workload-intelligence evaluation (obs/workload.py): the fleet's
    op-hotspot table (cost-dominant step kinds with per-kind
    seconds/bytes evidence) and cross-query subplan overlap candidates.
    Same three sources as ``advisor``: local window, a remote
    exporter's ``/workload`` with ``--url``, or ``--history`` offline
    replay.  Exits 0 whenever a verdict was produced.
``views``
    the semantic-cache / materialized-view state (views.views_payload):
    registered views with batch counts, staleness, hit counts, and last
    refresh time, plus the subplan cache's hit-rate line and the
    workload advisor's semantic outcome feed.  Local in-process state
    by default, a remote exporter's ``/views`` with ``--url``.

Rendering is a pure function of the ``/queries`` JSON payload
(:func:`render_top`) / the advisor payloads (:func:`render_advisor`,
:func:`render_workload`), so tests drive them with synthetic snapshots
and the remote and local paths share one code path.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import List, Optional

_BAR_WIDTH = 24


def _human(n: float) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000:
            return f"{n:.0f}{unit}" if unit else f"{n:.0f}"
        n /= 1000.0
    return f"{n:.0f}P"


def _bar(done: int, total: int, width: int = _BAR_WIDTH) -> str:
    if total <= 0:
        return "[" + "·" * width + "]"
    filled = min(width, int(round(width * done / total)))
    return "[" + "#" * filled + "-" * (width - filled) + "]"


def _fmt_query(q: dict) -> List[str]:
    eta = q.get("eta_seconds")
    lines = [
        "  q{qid:<5} {mode:<12} {phase:<12} {elapsed:>8.1f}s "
        "{done:>5}/{total:<5} inflight={inflight:<2} "
        "{rps:>9} rows/s  ici={ici:>6}B  hbm={hbm:>6}B{eta}".format(
            qid=q["query_id"], mode=q["mode"], phase=q["phase"],
            elapsed=q["elapsed_seconds"], done=q["batches_done"],
            total=q["total_batches"] or "?", inflight=q["inflight"],
            rps=_human(q["rows_per_sec"]), ici=_human(q["ici_bytes"]),
            hbm=_human(q["hbm_peak_bytes"]),
            eta=f"  eta={eta:.0f}s" if eta else "")]
    rung = q["recovery"]["last_rung"]
    if rung:
        lines.append(f"         recovery: {rung} "
                     f"({q['recovery']['count']} rungs)")
    shard_batches = q.get("shard_batches") or {}
    if shard_batches:
        total = max(q["batches_in"], max(shard_batches.values()), 1)
        for shard, done in sorted(shard_batches.items(),
                                  key=lambda kv: int(kv[0])):
            lines.append(f"         shard {int(shard):>2} "
                         f"{_bar(done, total)} {done}/{total}")
    return lines


def render_top(snap: dict, source: str = "local") -> str:
    """One frame of the ``top`` view from a ``/queries`` payload."""
    in_flight = snap.get("in_flight", [])
    queued = snap.get("queued", [])
    recent = snap.get("recent", [])
    ts = time.strftime("%H:%M:%S",
                       time.localtime(snap.get("unix_time", time.time())))
    lines = [f"srt top — {source} pid={snap.get('pid', '?')} {ts}  "
             f"running={len(in_flight)} queued={len(queued)} "
             f"recent={len(recent)}"]
    if in_flight:
        lines.append("in-flight:")
        for q in in_flight:
            lines.extend(_fmt_query(q))
    else:
        lines.append("in-flight: (none)")
    if queued:
        lines.append("queued:")
        for q in queued[:8]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} waiting "
                "{waited:>6.1f}s  est_hbm={est} fp={fp}".format(
                    qid=q.get("query_id", "?"), mode=q.get("mode", "?"),
                    status=q.get("status", "?"),
                    waited=q.get("queued_seconds", 0.0),
                    est=q.get("estimate_hbm_bytes", 0),
                    fp=q.get("fingerprint", "")))
    if recent:
        lines.append("recent:")
        for q in recent[-8:]:
            lines.append(
                "  q{qid:<5} {mode:<12} {status:<8} {elapsed:>8.1f}s "
                "{batches:>5} batches {rows:>10} rows out".format(
                    qid=q["query_id"], mode=q["mode"], status=q["status"],
                    elapsed=q["elapsed_seconds"],
                    batches=q["batches_done"], rows=q["rows_out"]))
    return "\n".join(lines)


def render_advisor(payload: dict, source: str = "local") -> str:
    """Console rendering of one ``/capacity`` advisor payload — pure."""
    snap = payload.get("snapshot") or {}
    busy = snap.get("busy", {})
    queue = snap.get("queue", {})
    ll = snap.get("littles_law", {})
    adm = snap.get("admission", {})
    lines = [
        f"srt advisor — {source}  verdict={payload.get('verdict', '?')}",
        "window={w:.0f}s  busy={b:.2f}  eff_concurrency={l:.2f}/{cap}  "
        "util_of_cap={u:.2f}  qps={qps:.2f}".format(
            w=snap.get("window_seconds", 0.0),
            b=busy.get("dispatch_fraction", 0.0),
            l=ll.get("effective_concurrency", 0.0),
            cap=ll.get("max_concurrent", "?"),
            u=ll.get("utilization_of_cap", 0.0),
            qps=ll.get("arrival_rate_qps", 0.0)),
        "queue: waits={n} p95={p95:.3f}s depth={d}   admission: "
        "hbm_waits={hw} rejected={rj}".format(
            n=queue.get("waits", 0), p95=queue.get("wait_p95_s", 0.0),
            d=queue.get("depth", 0), hw=adm.get("hbm_waits", 0),
            rj=adm.get("rejected", 0)),
    ]
    recs = payload.get("recommendations") or []
    cands = payload.get("candidates") or []
    shown = recs if recs else cands
    tag = "recommendations" if recs else "candidates (unconfirmed)"
    if not shown:
        lines.append("recommendations: (none — capacity looks healthy)")
        return "\n".join(lines)
    lines.append(f"{tag}:")
    for rec in shown:
        lines.append(f"  [{rec['severity']:>3}] {rec['action']}: "
                     f"{rec['reason']}")
        ev = rec.get("evidence") or {}
        if ev:
            detail = ", ".join(f"{k}={ev[k]}" for k in sorted(ev))
            lines.append(f"        evidence: {detail}")
    return "\n".join(lines)


def _capacity_pane(url: Optional[str]) -> List[str]:
    """Capacity summary lines appended under a ``top`` frame —
    best-effort (an older exporter without ``/capacity`` just yields
    nothing)."""
    try:
        if url is not None:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/capacity", timeout=5) as resp:
                payload = json.loads(resp.read().decode())
        else:
            from . import capacity
            payload = capacity.advise()
    except Exception:
        return []
    return ["", render_advisor(payload, source="capacity")]


def _advisor_payload(url: Optional[str], history: Optional[str],
                     last: int) -> dict:
    """The advisor payload from one of the three sources: a remote
    exporter's ``/capacity``, an offline metrics-history replay, or the
    local in-process window."""
    if url is not None:
        with urllib.request.urlopen(url.rstrip("/") + "/capacity",
                                    timeout=5) as resp:
            return json.loads(resp.read().decode())
    if history is not None:
        return _advise_history(history, last)
    from . import capacity
    return capacity.advise()


def _history_records(path: str, last: int) -> List[dict]:
    """The newest ``last`` metrics-history records, oldest first —
    the shared front half of every offline replay, on
    :func:`obs.history.iter_records` (tail-seeking reverse reader, so a
    multi-GB JSONL costs one tail read)."""
    from .history import iter_records
    records = list(iter_records(path, last=max(last, 1)))
    records.reverse()           # oldest first for the serialized replay
    return records


def _advise_history(path: str, last: int) -> dict:
    """Offline advisor: replay the newest ``last`` metrics-history
    records through the same pure derive/recommend core.  One-shot
    evaluation — hysteresis needs repeated windows — so a fresh
    ``Advisor(confirm=1)`` folds the single window."""
    from ..config import capacity_targets
    from . import capacity
    records = _history_records(path, last)
    events, w0, w1 = capacity.events_from_history(records)
    from ..config import (result_cache_bytes, serve_hbm_budget,
                          serve_max_concurrent)
    snap = capacity.derive(
        events, w0, w1, max_concurrent=serve_max_concurrent(),
        hbm_budget=serve_hbm_budget(),
        result_cache_on=result_cache_bytes() is not None)
    candidates = capacity.recommend(snap, capacity_targets())
    recs = capacity.Advisor(confirm=1, clear=1).observe(candidates)
    return {"snapshot": snap, "candidates": candidates,
            "recommendations": recs,
            "verdict": capacity.verdict_for(recs if recs else candidates)}


def render_workload(payload: dict, source: str = "local") -> str:
    """Console rendering of one ``/workload`` payload — pure."""
    snap = payload.get("snapshot") or {}
    lines = [
        f"srt workload — {source}  verdict={payload.get('verdict', '?')}",
        "window={w:.0f}s  queries={q}  plans={p}  step_seconds={s:.3f}  "
        "tickets={t}".format(
            w=snap.get("window_seconds", 0.0),
            q=snap.get("queries", 0), p=snap.get("plans", 0),
            s=snap.get("step_seconds", 0.0),
            t=snap.get("tickets", 0)),
    ]
    hotspots = snap.get("hotspots") or []
    if hotspots:
        lines.append("op hotspots (by attributed seconds):")
        for h in hotspots:
            p95 = h.get("per_row_p95_s")
            lines.append(
                "  {kind:<24} {sec:>9.4f}s {share:>5.0%}  "
                "queries={q:<3} bytes={b:>7} ici={ici:.4f}s "
                "syncs={hs:.0f}  p95/row={p95}  win~{win:.4f}s".format(
                    kind=h["kind"], sec=h["seconds"], share=h["share"],
                    q=h["queries"], b=_human(h["bytes"]),
                    ici=h["ici_seconds"], hs=h["host_syncs"],
                    p95=f"{p95:.2e}s" if p95 is not None else "n/a",
                    win=h["projected_win_s"]))
    else:
        lines.append("op hotspots: (none — window is empty)")
    overlaps = snap.get("overlaps") or []
    if overlaps:
        lines.append("overlap candidates (by benefit score):")
        for o in overlaps:
            lines.append(
                "  {fp} depth={d} {kinds:<32} x{n} plans={p} "
                "inflight={i} mean={m:.4f}s est={b}B score={s}".format(
                    fp=o["prefix_fingerprint"], d=o["depth"],
                    kinds=" > ".join(o["kinds"]), n=o["count"],
                    p=o["plans"], i=o["inflight"], m=o["seconds_mean"],
                    b=_human(o["est_result_bytes"]),
                    s=_human(o["benefit_score"])))
    else:
        lines.append("overlap candidates: (none recurring)")
    kern = payload.get("kernels") or {}
    enabled = kern.get("enabled") or []
    if enabled:
        lines.append("pallas kernels (SRT_KERNELS="
                     + ",".join(enabled) + "):")
        for name, st in sorted((kern.get("per_kernel") or {}).items()):
            sp = st.get("measured_speedup")
            lines.append(
                "  {name:<8} invocations={inv:<5} fallbacks={fb:<3} "
                "kernel_s={sec:.4f}  measured_speedup={sp}".format(
                    name=name, inv=st.get("invocations", 0),
                    fb=st.get("fallbacks", 0),
                    sec=st.get("seconds", 0.0),
                    sp=f"{sp:.2f}x" if sp else "n/a"))
        if kern.get("quarantined"):
            lines.append("  quarantined: "
                         + ", ".join(kern["quarantined"]))
    else:
        lines.append("pallas kernels: (none enabled — jnp oracle paths)")
    recs = payload.get("recommendations") or []
    cands = payload.get("candidates") or []
    shown = recs if recs else cands
    tag = "recommendations" if recs else "candidates (unconfirmed)"
    if not shown:
        lines.append("recommendations: (none — workload looks quiet)")
        return "\n".join(lines)
    lines.append(f"{tag}:")
    for rec in shown:
        lines.append(f"  [{rec['severity']:>3}] {rec['action']}: "
                     f"{rec['reason']}")
        ev = rec.get("evidence") or {}
        if ev:
            detail = ", ".join(f"{k}={ev[k]}" for k in sorted(ev))
            lines.append(f"        evidence: {detail}")
    return "\n".join(lines)


def _workload_pane(url: Optional[str]) -> List[str]:
    """Workload summary lines appended under a ``top`` frame —
    best-effort, like :func:`_capacity_pane`."""
    try:
        if url is not None:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/workload", timeout=5) as resp:
                payload = json.loads(resp.read().decode())
        else:
            from . import workload
            payload = workload.advise()
    except Exception:
        return []
    return ["", render_workload(payload, source="workload")]


def _workload_payload(url: Optional[str], history: Optional[str],
                      last: int) -> dict:
    """The workload payload from one of the three sources: a remote
    exporter's ``/workload``, an offline metrics-history replay, or the
    local in-process window."""
    if url is not None:
        with urllib.request.urlopen(url.rstrip("/") + "/workload",
                                    timeout=5) as resp:
            return json.loads(resp.read().decode())
    if history is not None:
        return _workload_history(history, last)
    from . import workload
    return workload.advise()


def _workload_history(path: str, last: int) -> dict:
    """Offline workload intelligence: replay the newest ``last``
    metrics-history records through the same pure derive/recommend
    core.  One-shot evaluation, so a fresh ``Advisor(confirm=1)`` folds
    the single window (the same discipline as :func:`_advise_history`)."""
    from ..config import workload_topk
    from . import workload
    records = _history_records(path, last)
    norm, window = workload.records_from_history(records)
    snap = workload.derive(norm, [], window, topk=workload_topk())
    candidates = workload.recommend(snap)
    recs = workload.Advisor(confirm=1, clear=1).observe(candidates)
    return {"snapshot": snap, "candidates": candidates,
            "recommendations": recs,
            "kernels": workload.kernels_block(),
            "verdict": workload.verdict_for(recs if recs else candidates)}


def render_views(payload: dict, source: str = "local") -> str:
    """Console rendering of one ``/views`` payload — pure."""
    sem = payload.get("semantic_cache") or {}
    outcomes = payload.get("outcomes") or {}
    lines = [
        f"srt views — {source}  views_enabled="
        f"{payload.get('views_enabled', False)}  "
        f"auto={payload.get('views_auto', False)}",
        "semantic cache: enabled={en}  entries={n}  bytes={b}/{cap}  "
        "hits={h} misses={m} hit_rate={hr:.0%}  materialized={mt} "
        "evicted={ev}".format(
            en=sem.get("enabled", False), n=sem.get("entries", 0),
            b=_human(sem.get("bytes", 0)),
            cap=_human(sem.get("cap_bytes", 0) or 0),
            h=sem.get("hits", 0), m=sem.get("misses", 0),
            hr=sem.get("hit_rate", 0.0),
            mt=sem.get("materializations", 0),
            ev=sem.get("evictions", 0)),
    ]
    confirmed = sem.get("confirmed_prefixes") or []
    if confirmed:
        lines.append("confirmed prefixes: " + " ".join(confirmed))
    views = payload.get("views") or []
    if views:
        lines.append("materialized views:")
        for v in views:
            last = v.get("last_refresh_s")
            lines.append(
                "  {name:<28}{auto} batches={b:<4} rows={r:>8} "
                "{state:<6} refreshes={rf:<3} hits={h:<3} "
                "last_refresh={last}".format(
                    name=v["name"], auto=" [auto]" if v.get("auto") else "",
                    b=v.get("batches", 0), r=_human(v.get("rows", 0)),
                    state="STALE" if v.get("stale") else "fresh",
                    rf=v.get("refreshes", 0), h=v.get("hits", 0),
                    last=f"{last:.4f}s" if last is not None else "never"))
    else:
        lines.append("materialized views: (none registered)")
    cold = outcomes.get("cold_evicted") or []
    if cold:
        lines.append("cold-evicted prefixes (advisor damped): "
                     + " ".join(cold))
    return "\n".join(lines)


def _views_payload(url: Optional[str]) -> dict:
    """The views payload from a remote exporter's ``/views`` or the
    local in-process registries."""
    if url is not None:
        with urllib.request.urlopen(url.rstrip("/") + "/views",
                                    timeout=5) as resp:
            return json.loads(resp.read().decode())
    from ..views import views_payload
    return views_payload()


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/queries",
                                timeout=5) as resp:
        return json.loads(resp.read().decode())


def _snapshot(url: Optional[str]) -> dict:
    if url is not None:
        return _fetch(url)
    from . import live
    return live.snapshot_all()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.obs",
        description="Console views over the live-query registry.")
    sub = parser.add_subparsers(dest="command")
    top = sub.add_parser("top", help="htop-style live query table")
    top.add_argument("--url", default=None,
                     help="remote exporter base URL (e.g. "
                          "http://127.0.0.1:9465); default: the local "
                          "in-process registry")
    top.add_argument("--interval", type=float, default=1.0,
                     help="refresh period in seconds (default 1.0)")
    top.add_argument("--once", action="store_true",
                     help="print one frame and exit")
    doctor = sub.add_parser(
        "doctor", help="explain a failed/slow query from its postmortem "
                       "bundle or plan fingerprint")
    doctor.add_argument("target",
                        help="path to a postmortem bundle JSON "
                             "(SRT_BUNDLE_DIR) or a plan fingerprint "
                             "with history records")
    doctor.add_argument("--history", default=None,
                        help="metrics-history JSONL for the baseline "
                             "(default: SRT_METRICS_HISTORY)")
    advisor = sub.add_parser(
        "advisor", help="capacity snapshot + ranked autoscaling advice")
    advisor.add_argument("--url", default=None,
                         help="remote exporter base URL (fetches its "
                              "/capacity); default: the local in-process "
                              "event window")
    advisor.add_argument("--history", default=None,
                         help="replay a metrics-history JSONL offline "
                              "instead of a live window")
    advisor.add_argument("--last", type=int, default=256,
                         help="history records to replay (newest first, "
                              "default 256)")
    advisor.add_argument("--json", action="store_true",
                         help="print the raw advisor payload as JSON")
    workload_p = sub.add_parser(
        "workload", help="fleet op-hotspot table + cross-query subplan "
                         "overlap candidates")
    workload_p.add_argument("--url", default=None,
                            help="remote exporter base URL (fetches its "
                                 "/workload); default: the local "
                                 "in-process window")
    workload_p.add_argument("--history", default=None,
                            help="replay a metrics-history JSONL offline "
                                 "instead of a live window")
    workload_p.add_argument("--last", type=int, default=256,
                            help="history records to replay (newest "
                                 "first, default 256)")
    workload_p.add_argument("--json", action="store_true",
                            help="print the raw workload payload as JSON")
    views_p = sub.add_parser(
        "views", help="semantic-cache stats + materialized-view table")
    views_p.add_argument("--url", default=None,
                         help="remote exporter base URL (fetches its "
                              "/views); default: the local in-process "
                              "registries")
    views_p.add_argument("--json", action="store_true",
                         help="print the raw views payload as JSON")
    args = parser.parse_args(argv)
    if args.command == "doctor":
        from .doctor import main as doctor_main
        return doctor_main(args.target, history_path=args.history)
    if args.command == "advisor":
        payload = _advisor_payload(args.url, args.history, args.last)
        if args.json:
            print(json.dumps(payload, sort_keys=True))
        else:
            print(render_advisor(
                payload, source=args.url or args.history or "local"))
        return 0
    if args.command == "workload":
        payload = _workload_payload(args.url, args.history, args.last)
        if args.json:
            print(json.dumps(payload, sort_keys=True))
        else:
            print(render_workload(
                payload, source=args.url or args.history or "local"))
        return 0
    if args.command == "views":
        payload = _views_payload(args.url)
        if args.json:
            print(json.dumps(payload, sort_keys=True))
        else:
            print(render_views(payload, source=args.url or "local"))
        return 0
    if args.command != "top":
        parser.print_help()
        return 2
    source = args.url or "local"
    try:
        while True:
            frame = render_top(_snapshot(args.url), source=source)
            frame += "\n".join(_capacity_pane(args.url))
            frame += "\n".join(_workload_pane(args.url))
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
