"""Tests for the build/packaging/config/aux-subsystem layer.

Covers the analogs of the reference's build-info stamping (buildtools/build-info, the reference's build/build-info),
`-D` property surface (pom.xml:76-103), NVTX toggle, and the
refcount-leak-debug contract (`-Dai.rapids.refcount.debug`)."""

import logging
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parent.parent


class TestBuildInfoScript:
    def test_emits_all_fields(self):
        out = subprocess.run(
            ["bash", str(ROOT / "buildtools" / "build-info"), "1.2.3", str(ROOT)],
            capture_output=True, text=True, check=True).stdout
        props = dict(line.split("=", 1) for line in out.strip().splitlines())
        assert props["version"] == "1.2.3"
        for key in ("user", "revision", "branch", "date", "url"):
            assert key in props
        # revision is the live git HEAD of this repo
        head = subprocess.run(["git", "rev-parse", "HEAD"], cwd=ROOT,
                              capture_output=True, text=True).stdout.strip()
        assert props["revision"] == head

    def test_requires_version_arg(self):
        proc = subprocess.run(["bash", str(ROOT / "buildtools" / "build-info")],
                              capture_output=True, text=True)
        assert proc.returncode != 0


class TestBuildInfoModule:
    def test_properties_dev_tree(self):
        from spark_rapids_tpu import __version__, build_info
        props = build_info.properties()
        assert props["version"] == __version__
        assert props["source"] in ("git", "wheel")
        assert len(props["revision"]) in (7, 40) or props["revision"] == "unknown"

    def test_properties_wheel_stamp(self, tmp_path, monkeypatch):
        from spark_rapids_tpu import build_info
        stamp = tmp_path / build_info.PROPERTIES_FILE
        stamp.write_text("version=9.9.9\nrevision=deadbeef\nbranch=rel\n"
                         "user=ci\ndate=2026-01-01T00:00:00Z\nurl=none\n")
        monkeypatch.setattr(build_info, "_PKG_DIR", tmp_path)
        props = build_info.properties()
        assert props == {"version": "9.9.9", "revision": "deadbeef",
                         "branch": "rel", "user": "ci",
                         "date": "2026-01-01T00:00:00Z", "url": "none",
                         "source": "wheel"}

    def test_banner(self):
        from spark_rapids_tpu import build_info
        b = build_info.banner()
        assert "spark-rapids-tpu" in b and "rev" in b

    def test_native_matches_python_version(self):
        from spark_rapids_tpu import __version__, build_info
        info = build_info.native_build_info()
        assert info["version"] == __version__


class TestConfig:
    def test_rows_impl_default_and_override(self, monkeypatch):
        from spark_rapids_tpu import config
        monkeypatch.delenv("SRT_ROWS_IMPL", raising=False)
        assert config.rows_impl() == "xla"
        monkeypatch.setenv("SRT_ROWS_IMPL", "pallas")
        assert config.rows_impl() == "pallas"
        monkeypatch.setenv("SRT_ROWS_IMPL", "cuda")
        with pytest.raises(ValueError):
            config.rows_impl()

    def test_flags_parse_truthy(self, monkeypatch):
        from spark_rapids_tpu import config
        for raw, want in (("1", True), ("true", True), ("ON", True),
                          ("0", False), ("no", False), ("", False)):
            monkeypatch.setenv("SRT_TRACE", raw)
            assert config.trace_enabled() is want
        monkeypatch.delenv("SRT_TRACE")
        assert config.trace_enabled() is False

    def test_log_level(self, monkeypatch):
        from spark_rapids_tpu import config
        monkeypatch.delenv("SRT_LOG_LEVEL", raising=False)
        assert config.log_level() == logging.WARNING
        monkeypatch.setenv("SRT_LOG_LEVEL", "debug")
        assert config.log_level() == logging.DEBUG
        monkeypatch.setenv("SRT_LOG_LEVEL", "nope")
        with pytest.raises(ValueError):
            config.log_level()

    def test_knob_table_lists_every_knob(self):
        from spark_rapids_tpu import config
        table = config.knob_table()
        assert "SRT_ROWS_IMPL" in table and "SRT_LEAK_DEBUG" in table


class TestTracing:
    def test_noop_when_disabled(self, monkeypatch):
        monkeypatch.delenv("SRT_TRACE", raising=False)
        from spark_rapids_tpu.utils.tracing import trace, traced
        with trace("scope"):
            x = 1

        @traced
        def f(a):
            return a + 1

        assert f(x) == 2

    def test_annotates_when_enabled(self, monkeypatch):
        monkeypatch.setenv("SRT_TRACE", "1")
        from spark_rapids_tpu.utils.tracing import trace

        # TraceAnnotation works outside an active capture; just verify the
        # scope body executes under the annotation without error.
        with trace("srt-test-scope"):
            assert True


class TestRowBlobsHandle:
    SCHEMA = None

    def _convert(self):
        from spark_rapids_tpu import ffi
        from spark_rapids_tpu.dtypes import INT32, INT64
        schema = (INT64, INT32)
        datas = [np.arange(100, dtype=np.int64),
                 np.arange(100, dtype=np.int32)]
        valids = [np.ones(100, np.uint8), None]
        return ffi.convert_to_rows_handle(schema, datas, valids)

    def test_context_manager_lifecycle(self):
        with self._convert() as blobs:
            assert len(blobs) == 1
            assert blobs.num_rows(0) == 100
            assert blobs.row_size(0) == 16
            view = blobs.data(0)
            assert view.nbytes == 1600
        assert blobs.closed

    def test_use_after_close_raises(self):
        from spark_rapids_tpu.ffi import NativeError
        blobs = self._convert()
        blobs.close()
        blobs.close()  # idempotent
        with pytest.raises(NativeError):
            blobs.data(0)

    def test_leak_report_at_exit(self):
        """SRT_LEAK_DEBUG=1 must report unclosed handles on interpreter exit
        with the creation stack (the refcount.debug contract)."""
        code = (
            "import numpy as np\n"
            "from spark_rapids_tpu import ffi\n"
            "from spark_rapids_tpu.dtypes import INT64\n"
            "b = ffi.convert_to_rows_handle((INT64,), [np.arange(4, dtype=np.int64)], [None])\n"
            "print('blobs:', len(b))\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=ROOT, env={"PATH": "/usr/bin:/bin", "SRT_LEAK_DEBUG": "1",
                           "JAX_PLATFORMS": "cpu", "HOME": "/root"})
        assert proc.returncode == 0, proc.stderr
        assert "LEAK" in proc.stderr
        assert "convert_to_rows_handle" in proc.stderr

    def test_no_leak_report_when_closed(self):
        code = (
            "import numpy as np\n"
            "from spark_rapids_tpu import ffi\n"
            "from spark_rapids_tpu.dtypes import INT64\n"
            "with ffi.convert_to_rows_handle((INT64,), [np.arange(4, dtype=np.int64)], [None]) as b:\n"
            "    pass\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            cwd=ROOT, env={"PATH": "/usr/bin:/bin", "SRT_LEAK_DEBUG": "1",
                           "JAX_PLATFORMS": "cpu", "HOME": "/root"})
        assert proc.returncode == 0, proc.stderr
        assert "LEAK" not in proc.stderr
