// JVM host sample for the srt_* C ABI via Panama FFM (JDK 22+).
//
// The reference serves the JVM through JNI (RowConversion.java:101-121 ->
// RowConversionJni.cpp:24-66, with a hand-written native bridge per entry
// point).  This engine exposes a plain C ABI instead, so a modern JVM
// needs NO native glue at all: java.lang.foreign binds the symbols
// directly.  This program is the JVM twin of hosts/c/host_check.c — same
// spec-file protocol, same output bytes — so the byte-equality oracle in
// tests/test_host_interop.py applies to either host.
//
// Build/run (needs a JDK with java.lang.foreign, 22+):
//   javac RowConversionFfm.java
//   java --enable-native-access=ALL-UNNAMED RowConversionFfm \
//        <libspark_rapids_tpu_host.so> <spec> <out>
// ci/host-interop-check.sh invokes this automatically when a suitable JDK
// is on PATH and skips (like the reference's hardware-gated CuFileTest
// exclusion) when not.

import java.io.IOException;
import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.foreign.ValueLayout;
import java.lang.invoke.MethodHandle;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.file.Files;
import java.nio.file.Path;
import java.nio.file.Paths;

public final class RowConversionFfm {

  public static void main(String[] args) throws Throwable {
    if (args.length != 3) {
      System.err.println("usage: RowConversionFfm <lib.so> <spec> <out>");
      System.exit(1);
    }
    Linker linker = Linker.nativeLinker();
    try (Arena arena = Arena.ofConfined()) {
      SymbolLookup lib = SymbolLookup.libraryLookup(Paths.get(args[0]), arena);

      MethodHandle convert = linker.downcallHandle(
          lib.find("srt_convert_to_rows").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.JAVA_LONG,   // blob-set handle
              ValueLayout.JAVA_INT,                       // ncols
              ValueLayout.ADDRESS,                        // type_ids
              ValueLayout.ADDRESS,                        // scales
              ValueLayout.JAVA_LONG,                      // num_rows
              ValueLayout.ADDRESS,                        // col_data**
              ValueLayout.ADDRESS,                        // col_valid**
              ValueLayout.JAVA_LONG,                      // max_batch_bytes
              ValueLayout.JAVA_INT,                       // check_row_width
              ValueLayout.ADDRESS,                        // out_num_blobs
              ValueLayout.ADDRESS));                      // out_status
      MethodHandle blobsCount = linker.downcallHandle(
          lib.find("srt_blobs_count").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG));
      MethodHandle blobRows = linker.downcallHandle(
          lib.find("srt_blob_num_rows").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.JAVA_LONG, ValueLayout.JAVA_LONG,
              ValueLayout.JAVA_INT));
      MethodHandle blobRowSize = linker.downcallHandle(
          lib.find("srt_blob_row_size").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
              ValueLayout.JAVA_INT));
      MethodHandle blobData = linker.downcallHandle(
          lib.find("srt_blob_data").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.ADDRESS, ValueLayout.JAVA_LONG,
              ValueLayout.JAVA_INT));
      MethodHandle blobsFree = linker.downcallHandle(
          lib.find("srt_blobs_free").orElseThrow(),
          FunctionDescriptor.ofVoid(ValueLayout.JAVA_LONG));
      MethodHandle lastError = linker.downcallHandle(
          lib.find("srt_last_error").orElseThrow(),
          FunctionDescriptor.of(ValueLayout.ADDRESS));

      Spec spec = Spec.read(Paths.get(args[1]));

      MemorySegment typeIds = arena.allocateFrom(ValueLayout.JAVA_INT,
          spec.typeIds);
      MemorySegment scales = arena.allocateFrom(ValueLayout.JAVA_INT,
          spec.scales);
      MemorySegment dataPtrs = arena.allocate(ValueLayout.ADDRESS,
          spec.ncols);
      MemorySegment validPtrs = arena.allocate(ValueLayout.ADDRESS,
          spec.ncols);
      for (int c = 0; c < spec.ncols; c++) {
        MemorySegment d = arena.allocate(Math.max(spec.data[c].length, 1));
        MemorySegment.copy(spec.data[c], 0, d, ValueLayout.JAVA_BYTE, 0,
            spec.data[c].length);
        dataPtrs.setAtIndex(ValueLayout.ADDRESS, c, d);
        if (spec.valid[c] != null) {
          MemorySegment v = arena.allocate(Math.max(spec.valid[c].length, 1));
          MemorySegment.copy(spec.valid[c], 0, v, ValueLayout.JAVA_BYTE, 0,
              spec.valid[c].length);
          validPtrs.setAtIndex(ValueLayout.ADDRESS, c, v);
        } else {
          validPtrs.setAtIndex(ValueLayout.ADDRESS, c, MemorySegment.NULL);
        }
      }

      MemorySegment numBlobs = arena.allocate(ValueLayout.JAVA_INT);
      MemorySegment status = arena.allocate(ValueLayout.JAVA_INT);
      long handle = (long) convert.invoke(spec.ncols, typeIds, scales,
          spec.numRows, dataPtrs, validPtrs, 0L, 1, numBlobs, status);
      if (handle == 0) {
        MemorySegment err = (MemorySegment) lastError.invoke();
        throw new RuntimeException("srt_convert_to_rows failed ("
            + status.get(ValueLayout.JAVA_INT, 0) + "): "
            + err.reinterpret(4096).getString(0));
      }
      int n = (int) blobsCount.invoke(handle);
      if (n != numBlobs.get(ValueLayout.JAVA_INT, 0)) {
        throw new RuntimeException("blob count mismatch");
      }
      try (var out = Files.newOutputStream(Paths.get(args[2]))) {
        for (int i = 0; i < n; i++) {
          long rows = (long) blobRows.invoke(handle, i);
          int rowSize = (int) blobRowSize.invoke(handle, i);
          MemorySegment bytes = (MemorySegment) blobData.invoke(handle, i);
          byte[] buf = bytes.reinterpret(rows * rowSize)
              .toArray(ValueLayout.JAVA_BYTE);
          out.write(buf);
        }
      }
      blobsFree.invoke(handle);
      System.out.println("RowConversionFfm ok: " + n + " blob(s), "
          + spec.numRows + " rows");
    }
  }

  /** Parsed spec file (see hosts/c/host_check.c for the layout). */
  private record Spec(int ncols, long numRows, int[] typeIds, int[] scales,
                      byte[][] data, byte[][] valid) {

    static Spec read(Path path) throws IOException {
      ByteBuffer b = ByteBuffer.wrap(Files.readAllBytes(path))
          .order(ByteOrder.LITTLE_ENDIAN);
      int ncols = b.getInt();
      long numRows = b.getLong();
      int[] typeIds = new int[ncols];
      int[] scales = new int[ncols];
      byte[][] data = new byte[ncols][];
      byte[][] valid = new byte[ncols][];
      for (int c = 0; c < ncols; c++) {
        typeIds[c] = b.getInt();
        scales[c] = b.getInt();
        int elemSize = b.getInt();
        int hasValid = b.getInt();
        data[c] = new byte[(int) (numRows * elemSize)];
        b.get(data[c]);
        if (hasValid != 0) {
          valid[c] = new byte[(int) numRows];
          b.get(valid[c]);
        }
      }
      return new Spec(ncols, numRows, typeIds, scales, data, valid);
    }
  }
}
