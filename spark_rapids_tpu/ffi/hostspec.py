"""Host-interop spec files: the byte protocol shared with non-Python hosts.

The host samples (hosts/c/host_check.c, hosts/java/RowConversionFfm.java)
prove that a process with no Python in it can drive the srt_* C ABI — the
role the reference's JNI layer plays for the JVM (RowConversionJni.cpp).
This module writes their input: a little-endian spec file describing a
fixed-width table as raw column buffers.

Layout: int32 ncols, int64 num_rows, then per column
int32 type_id, int32 scale, int32 elem_size, int32 has_valid,
``num_rows * elem_size`` data bytes, ``num_rows`` validity bytes (0/1)
when has_valid.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

from ..table import Table


def write_spec(table: Table, path: str | Path) -> None:
    """Serialize a fixed-width table's host buffers to a spec file."""
    with open(path, "wb") as f:
        f.write(struct.pack("<iq", table.num_columns, table.num_rows))
        for _, col in table.items():
            if col.offsets is not None:
                raise TypeError("spec files carry fixed-width columns only")
            data = np.ascontiguousarray(np.asarray(col.data))
            f.write(struct.pack("<iiii", int(col.dtype.type_id),
                                col.dtype.scale, col.dtype.itemsize,
                                1 if col.validity is not None else 0))
            f.write(data.tobytes())
            if col.validity is not None:
                f.write(np.asarray(col.validity).astype(np.uint8).tobytes())


def expected_row_bytes(table: Table) -> bytes:
    """The Python/device path's row-format bytes for the same table —
    the byte-equality oracle the host programs are checked against."""
    from ..rows import convert as rc
    from ..rows.image import words_to_host_bytes
    blobs = rc.to_rows(table)
    return b"".join(bytes(words_to_host_bytes(b.words, b.row_size))
                    for b in blobs)
