"""Parquet scan benchmark: native device decoder vs Arrow host reader.

Measures end-to-end file→device-Table throughput for both engines on the
same file (4M-row mixed fixed-width + dictionary-string schema, snappy).
IO noise is minimized by tmpfs-or-page-cache residency (the file is read
multiple times; first pass primes the cache).  The native path's win
condition is the decode itself: RLE/dictionary expansion and null scatter
on device instead of pyarrow's host threads.

Run: python benchmarks/bench_parquet.py
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

N = 4_000_000
REPS = 3


def main():
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io import read_parquet

    rng = np.random.default_rng(17)
    vocab = np.asarray([f"cat-{i:03d}" for i in range(200)])
    at = pa.table({
        "i64": pa.array(rng.integers(-1 << 40, 1 << 40, N),
                        mask=rng.random(N) < 0.1),
        "f64": rng.normal(size=N),
        "i32": rng.integers(-1 << 20, 1 << 20, N).astype(np.int32),
        "s": pa.array(vocab[rng.integers(0, len(vocab), N)]),
    })

    with tempfile.TemporaryDirectory() as d:
        # One distinct file per rep: identical repeated device inputs can be
        # served from a repeated-computation cache through the TPU tunnel
        # (BASELINE.md measurement rule #2), so every read must differ.
        paths = []
        for r in range(REPS):
            p = Path(d) / f"bench-{r}.parquet"
            at2 = at.set_column(1, "f64", pa.array(
                np.asarray(at["f64"]) + float(r)))
            pq.write_table(at2, p, compression="snappy",
                           row_group_size=1 << 20)
            paths.append(p)

        for engine in ("native", "arrow"):
            t = read_parquet(paths[-1], engine=engine)  # warm: cache + jit
            _ = np.asarray(t["i64"].data[-1:])
            t0 = time.perf_counter()
            for p in paths:
                t = read_parquet(p, engine=engine)
            _ = np.asarray(t["i64"].data[-1:])          # fence
            dt = (time.perf_counter() - t0) / REPS
            print(json.dumps({"metric": f"parquet_scan_{engine}_4M",
                              "value": round(N / dt, 1),
                              "unit": "rows/sec"}))


if __name__ == "__main__":
    main()
