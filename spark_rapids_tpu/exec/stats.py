"""Per-column statistics with identity caching.

The dense-domain group-by path needs a static (lo, hi) range per key.  When
the plan author doesn't pin one (``domains=``), the binder probes the column
once — a device min/max reduction plus ONE host sync — and caches the result
against the column's device buffer identity, so repeated plan runs over the
same bound table (the steady state of a Spark executor processing a cached
relation) never sync again.

This is the engine's seed of a statistics subsystem (the reference delegates
stats to Spark's catalog; here they are measured on device).
"""

from __future__ import annotations

import weakref
from typing import Optional

import jax
import jax.numpy as jnp

from ..column import Column
from ..utils.memory import record_host_sync

#: (id(data), id(validity) or None) -> ((weakrefs), (lo, hi)).  The cache
#: identity is the *pair* of device buffers — two columns may share a data
#: buffer under different validity masks and must not see each other's
#: range; weakref guards keep collected-buffer ids from aliasing.
_CACHE: dict = {}


def _guarded_cache_get(cache: dict, key, buffers) -> object:
    hit = cache.get(key)
    if hit is not None and all(r() is b for r, b in zip(hit[0], buffers)):
        return hit[1]
    return None


def _guarded_cache_put(cache: dict, key, buffers, value) -> None:
    try:
        refs = tuple(
            weakref.ref(b, lambda _r, _k=key: cache.pop(_k, None))
            for b in buffers)
    except TypeError:                    # buffer type not weakref-able
        return
    cache[key] = (refs, value)


def column_int_range(col: Column,
                     extra_mask=None) -> Optional[tuple[int, int]]:
    """(min, max) over valid rows of an integer/bool column, cached.

    ``extra_mask`` restricts the probe to its True rows (a sharded
    table's live-row mask: padding slots must not widen the domain).
    Returns None for empty/all-null columns (no dense domain exists).
    Costs one host sync on first probe of a given (data, validity[,
    mask]) buffer set.
    """
    data = col.data
    buffers = tuple(b for b in (data, col.validity, extra_mask)
                    if b is not None)
    key = tuple(id(b) for b in buffers)
    hit = _guarded_cache_get(_CACHE, key, buffers)
    if hit is not None:
        return hit

    if col.size == 0:
        return None
    valid = col.validity
    if extra_mask is not None:
        valid = extra_mask if valid is None else (valid & extra_mask)
    if valid is not None:
        lo = jnp.min(jnp.where(valid, data, jnp.iinfo(data.dtype).max))
        hi = jnp.max(jnp.where(valid, data, jnp.iinfo(data.dtype).min))
        # One batched transfer (a blocking round trip costs ~400 ms on a
        # tunneled device; three separate int()/bool() reads would triple it).
        lo_v, hi_v, ok = jax.device_get((lo, hi, jnp.any(valid)))
        record_host_sync("stats.probe", 17)
        if not bool(ok):
            return None
        lo_v, hi_v = int(lo_v), int(hi_v)
    else:
        lo_v, hi_v = map(int, jax.device_get((jnp.min(data), jnp.max(data))))
        record_host_sync("stats.probe", 16)

    result = (lo_v, hi_v)
    _guarded_cache_put(_CACHE, key, buffers, result)
    return result
