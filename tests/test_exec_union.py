"""Unit tests for the round-5 plan primitives: compiled UNION ALL,
grouping sets / ROLLUP, set-op helpers, and literal projections.

Every compiled result is cross-checked against the eager oracle
(run_plan_eager) and, for the numeric cores, a pandas reference — the
same oracle discipline as the TPC-DS bank (SURVEY.md §4).
"""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.column import Column
from spark_rapids_tpu.dtypes import INT32, STRING
from spark_rapids_tpu.exec import (col, except_keys, intersect_keys, lit,
                                   plan)
from spark_rapids_tpu.exec.compile import run_plan_eager
from spark_rapids_tpu.table import Table


def _table(rng, n, klo=0, khi=10, with_null=True):
    k = rng.integers(klo, khi, n).astype(np.int64)
    v = np.round(rng.uniform(-10, 10, n), 3)
    kv = rng.random(n) >= 0.1 if with_null else None
    vv = rng.random(n) >= 0.1 if with_null else None
    return Table([
        ("k", Column.from_numpy(k, validity=kv)),
        ("v", Column.from_numpy(v, validity=vv)),
    ])


def _pdf(t):
    return pd.DataFrame({c: pd.array(t[c].to_pylist()) for c in t.names})


def _sorted_records(t):
    df = _pdf(t)
    return sorted(
        df.itertuples(index=False, name=None),
        key=lambda r: tuple((x is None or x != x, x if (
            x is not None and x == x) else 0) for x in r))


def assert_tables_equal(got, want, float_cols=()):
    assert set(got.names) == set(want.names)
    gr = _sorted_records(got.select(list(want.names)))
    wr = _sorted_records(want)
    assert len(gr) == len(wr), f"{len(gr)} vs {len(wr)} rows"
    for g, w in zip(gr, wr):
        for name, gv, wv in zip(want.names, g, w):
            if gv is None or (isinstance(gv, float) and gv != gv):
                assert wv is None or (isinstance(wv, float) and wv != wv), \
                    f"{name}: {gv} vs {wv}"
            elif name in float_cols:
                assert abs(gv - wv) < 1e-9 * max(1, abs(wv)), \
                    f"{name}: {gv} vs {wv}"
            else:
                assert gv == wv, f"{name}: {gv} vs {wv}"


class TestUnionAll:
    def test_raw_union_groupby(self, rng):
        t1, t2 = _table(rng, 500), _table(rng, 300)
        p = (plan().union_all(t2)
             .groupby_agg(["k"], [("v", "sum", "s"),
                                  ("v", "count", "c")])
             .sort_by(["k"]))
        assert_tables_equal(p.run(t1), run_plan_eager(p, t1),
                            float_cols=("s",))
        # pandas cross-check
        df = pd.concat([_pdf(t1), _pdf(t2)])
        want = (df.groupby("k", dropna=False)
                .agg(s=("v", "sum"), c=("v", "count")))
        got = _pdf(p.run(t1))
        got_nn = got[got.k.notna()].set_index("k").sort_index()
        want_nn = want[[i == i for i in want.index]].sort_index()
        np.testing.assert_allclose(
            got_nn.s.to_numpy(float), want_nn.s.to_numpy(float))
        np.testing.assert_array_equal(
            got_nn.c.to_numpy(int), want_nn.c.to_numpy(int))

    def test_branch_plan_with_filter_and_project(self, rng):
        t1, t2 = _table(rng, 400), _table(rng, 400)
        branch = (plan().filter(col("v") > 0)
                  .with_columns(v=col("v") * 2.0))
        p = (plan().filter(col("k") < 8)
             .union_all(t2, branch)
             .groupby_agg(["k"], [("v", "sum", "s")])
             .sort_by(["k"]))
        assert_tables_equal(p.run(t1), run_plan_eager(p, t1),
                            float_cols=("s",))

    def test_branch_with_broadcast_join(self, rng):
        t1, t2 = _table(rng, 300, khi=5), _table(rng, 200, khi=5)
        dim = Table([
            ("dk", Column.from_numpy(np.arange(5, dtype=np.int64))),
            ("w", Column.from_numpy(np.arange(5, dtype=np.float64))),
        ])
        branch = (plan().join_broadcast(dim, left_on="k", right_on="dk")
                  .with_columns(v=col("v") + col("w"))
                  .select("k", "v"))
        p = (plan().union_all(t2, branch)
             .groupby_agg(["k"], [("v", "sum", "s")]).sort_by(["k"]))
        assert_tables_equal(p.run(t1), run_plan_eager(p, t1),
                            float_cols=("s",))

    def test_three_way_union(self, rng):
        t1, t2, t3 = _table(rng, 200), _table(rng, 150), _table(rng, 100)
        p = (plan().union_all(t2).union_all(t3)
             .groupby_agg(["k"], [("v", "mean", "m")]).sort_by(["k"]))
        assert_tables_equal(p.run(t1), run_plan_eager(p, t1),
                            float_cols=("m",))

    def test_nested_union_in_branch(self, rng):
        t1, t2, t3 = _table(rng, 200), _table(rng, 150), _table(rng, 100)
        branch = plan().union_all(t3)
        p = (plan().union_all(t2, branch)
             .groupby_agg(["k"], [("v", "sum", "s")]).sort_by(["k"]))
        assert_tables_equal(p.run(t1), run_plan_eager(p, t1),
                            float_cols=("s",))

    def test_high_cardinality_sorted_groupby_after_union(self, rng):
        t1 = _table(rng, 600, khi=3000)
        t2 = _table(rng, 400, khi=3000)
        p = (plan().union_all(t2)
             .groupby_agg(["k"], [("v", "sum", "s")])
             .sort_by(["s"], ascending=[False]).limit(20))
        got, want = p.run(t1), run_plan_eager(p, t1)
        g, w = _pdf(got), _pdf(want)
        np.testing.assert_allclose(
            np.sort(g.s.to_numpy(float)), np.sort(w.s.to_numpy(float)))

    def test_schema_mismatch_raises(self, rng):
        t1 = _table(rng, 50)
        t2 = t1.rename({"v": "w"})
        with pytest.raises(TypeError, match="schema mismatch"):
            plan().union_all(t2).run(t1)

    def test_dtype_mismatch_raises(self, rng):
        t1 = _table(rng, 50)
        t2 = Table([("k", Column.from_numpy(
            np.arange(5, dtype=np.int64))),
            ("v", Column.from_numpy(np.arange(5, dtype=np.int64)))])
        with pytest.raises(TypeError, match="dtype mismatch"):
            plan().union_all(t2).run(t1)

    def test_string_state_raises(self, rng):
        t1 = Table([
            ("k", Column.from_numpy(np.arange(10, dtype=np.int64))),
            ("s", Column.from_pylist(list("abcdefghij"), STRING)),
        ])
        t2 = t1
        with pytest.raises(TypeError, match="string"):
            plan().union_all(t2).run(t1)

    def test_empty_branch_raises(self, rng):
        t1 = _table(rng, 50)
        t2 = Table([("k", Column.from_numpy(np.zeros(0, np.int64))),
                    ("v", Column.from_numpy(np.zeros(0, np.float64)))])
        with pytest.raises(ValueError, match="no rows"):
            plan().union_all(t2).run(t1)


class TestGroupingSets:
    def test_rollup_dense_matches_pandas(self, rng):
        t = _table(rng, 800, khi=6)
        t = t.with_column("k2", Column.from_numpy(
            rng.integers(0, 4, 800).astype(np.int64)))
        p = (plan().groupby_rollup(["k", "k2"], [("v", "sum", "s"),
                                                 ("v", "count", "c")])
             .sort_by(["lochierarchy", "k", "k2"]))
        got = p.run(t)
        assert_tables_equal(got, run_plan_eager(p, t), float_cols=("s",))
        # level-2 grand total vs pandas
        df = _pdf(t)
        total = got.select(["s", "c", "lochierarchy"])
        tdf = _pdf(total)
        grand = tdf[tdf.lochierarchy == 2]
        assert len(grand) == 1
        np.testing.assert_allclose(float(grand.s.iloc[0]),
                                   df.v.sum(), rtol=1e-9)
        assert int(grand.c.iloc[0]) == int(df.v.count())

    def test_rollup_sorted_path(self, rng):
        # High-cardinality key forces the sorted grouping-sets path.
        t = _table(rng, 700, khi=5000)
        p = (plan().groupby_rollup(["k"], [("v", "sum", "s"),
                                           ("v", "max", "mx")]))
        got, want = p.run(t), run_plan_eager(p, t)
        assert_tables_equal(got, want, float_cols=("s", "mx"))

    def test_explicit_grouping_sets(self, rng):
        t = _table(rng, 500, khi=5)
        t = t.with_column("k2", Column.from_numpy(
            rng.integers(0, 3, 500).astype(np.int64)))
        p = plan().groupby_grouping_sets(
            ["k", "k2"], [("v", "mean", "m")],
            sets=[["k"], ["k2"]], grouping_id="gid")
        assert_tables_equal(p.run(t), run_plan_eager(p, t),
                            float_cols=("m",))

    def test_rollup_with_nunique_sorted(self, rng):
        t = _table(rng, 400, khi=4)
        p = plan().groupby_rollup(["k"], [("v", "nunique", "nu")])
        assert_tables_equal(p.run(t), run_plan_eager(p, t))

    def test_first_rejected(self, rng):
        with pytest.raises(ValueError, match="not defined across"):
            plan().groupby_rollup(["k"], [("v", "first", "f")])

    def test_having_on_grouping_id(self, rng):
        t = _table(rng, 300, khi=4)
        p = (plan().groupby_rollup(["k"], [("v", "sum", "s")])
             .filter(col("lochierarchy").eq(1)))
        got = p.run(t)
        assert got.num_rows == 1
        assert got["k"].to_pylist() == [None]


class TestSetOps:
    def test_intersect_and_except(self, rng):
        a = _table(rng, 300, khi=40)
        b = _table(rng, 300, klo=20, khi=60)
        ka = {k for k in _pdf(a).k.dropna().astype(int)}
        kb = {k for k in _pdf(b).k.dropna().astype(int)}
        inter = intersect_keys(a, b, ["k"])
        exc = except_keys(a, b, ["k"])
        gi = {int(x) for x in inter["k"].to_pylist() if x is not None}
        ge = {int(x) for x in exc["k"].to_pylist() if x is not None}
        assert gi == (ka & kb)
        assert ge == (ka - kb)
        # null key tuples never match (SQL equi-join semantics), but
        # distinct keeps the null group on the left side
        null_left = any(x is None for x in _pdf(a).k)
        assert any(x is None for x in exc["k"].to_pylist()) == null_left


class TestLitProjection:
    def test_with_columns_lit(self, rng):
        t = _table(rng, 100)
        p = (plan().with_columns(one=lit(1))
             .groupby_agg(["one"], [("v", "count", "c")],
                          domains={"one": (1, 1)}))
        got = p.run(t)
        assert got["one"].to_pylist() == [1]
        assert_tables_equal(got, run_plan_eager(p, t))

    def test_select_lit_float_and_bool(self, rng):
        t = _table(rng, 10)
        p = plan().select("k", ("half", lit(0.5)), ("flag", lit(True)))
        got = p.run(t)
        assert got["half"].to_pylist() == [0.5] * 10
        assert got["flag"].to_pylist() == [True] * 10

    def test_string_lit_raises(self, rng):
        t = _table(rng, 10)
        with pytest.raises(TypeError, match="literal"):
            plan().select(("s", lit("x"))).run(t)
