#!/bin/bash
# Nightly CI: clean build + full suite + benchmark sweep.
#
# Reference analog: ci/nightly-build.sh:24-28 (clean GPU `mvn package`).
# The nightly additionally records benchmark JSON lines (bench.py is the
# driver-facing single-metric bench; benchmarks/ holds the query-shaped
# suite) into $BENCH_OUT for trend tracking.
set -ex

cd "$(dirname "$0")/.."

rm -rf dist/ build/
./ci/premerge-build.sh

BENCH_OUT="${BENCH_OUT:-dist/bench-nightly.jsonl}"
mkdir -p "$(dirname "$BENCH_OUT")"
# Benchmarks want the real device; skip gracefully on CPU-only runners.
if python -c 'import jax; assert jax.default_backend() != "cpu"' 2>/dev/null; then
    python bench.py | tee -a "$BENCH_OUT"
    python benchmarks/bench_queries.py --capacity --workload | tee -a "$BENCH_OUT"
    # Standalone lane: exits nonzero on any CSE-splice or view parity loss.
    python benchmarks/bench_queries.py --semantic | tee -a "$BENCH_OUT"
    # Pallas kernels vs jnp oracle: on-device this measures real compiled
    # kernels (the speedups the workload advisor cites); exits nonzero on
    # any parity loss or a kernel that never fired.
    python benchmarks/bench_queries.py --kernels | tee -a "$BENCH_OUT"
    # Out-of-core lane: oracle-vs-spilled wall + bytes paged; exits
    # nonzero on parity loss or a run that never actually paged.
    python benchmarks/bench_queries.py --spill | tee -a "$BENCH_OUT"
else
    echo "nightly: no accelerator on this runner; benchmarks skipped"
    # The kernel parity lane is still meaningful without an accelerator:
    # interpret mode runs the same kernel code on CPU.
    python benchmarks/bench_queries.py --kernels | tee -a "$BENCH_OUT"
    # Spill parity is HBM-budget arithmetic, not device behavior — the
    # CPU runner exercises the identical page-out/page-in path.
    python benchmarks/bench_queries.py --spill | tee -a "$BENCH_OUT"
fi
