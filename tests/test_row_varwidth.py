"""Variable-width row conversion tests.

The reference fails on non-fixed-width types (row_conversion.cu:514-516);
this engine extends the contract to strings.  Oracles:

* round-trip table equality (the reference's own strategy,
  RowConversionTest.java:29-59, extended to strings),
* a golden-byte oracle: an independent numpy builder of the documented
  layout (fixed slots + (len<<32|off) string slots + validity tail +
  tight var section + 8-byte row padding).
"""

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.rows import convert
from spark_rapids_tpu.rows.varwidth import (VarRowBlob, compute_var_layout,
                                            pack_var_rows, unpack_var_rows)


def _mixed_table(rng, n=257):
    words = ["", "a", "bb", "ccc", "d" * 17, "tail"]
    svals = [None if rng.random() < 0.15 else words[rng.integers(0, 6)]
             for _ in range(n)]
    s2 = [None if rng.random() < 0.5 else "x" * int(rng.integers(0, 9))
          for _ in range(n)]
    return Table([
        ("i64", Column.from_numpy(rng.integers(-1 << 40, 1 << 40, n).astype(np.int64),
                                  validity=rng.random(n) > 0.2)),
        ("s", Column.from_pylist(svals, dt.STRING)),
        ("i8", Column.from_numpy(rng.integers(-128, 128, n).astype(np.int8))),
        ("f32", Column.from_numpy(rng.normal(size=n).astype(np.float32),
                                  validity=rng.random(n) > 0.1)),
        ("s2", Column.from_pylist(s2, dt.STRING)),
    ])


def _oracle_bytes(table):
    """Independent numpy construction of the documented var-width layout."""
    schema = [c.dtype for c in table.columns]
    layout = compute_var_layout(tuple(schema))
    fx = layout.fixed
    rows = []
    n = table.num_rows
    pyd = table.to_pydict()
    names = list(table.names)
    for r in range(n):
        fixed = bytearray(fx.row_size)
        # var section first (to know offsets)
        var = bytearray()
        at = fx.row_size
        slot_vals = {}
        for i in layout.var_cols:
            v = pyd[names[i]][r]
            b = b"" if v is None else v.encode()
            slot_vals[i] = (len(b) << 32) | at
            var += b
            at += len(b)
        for i, c in enumerate(table.columns):
            start = fx.column_starts[i]
            if i in slot_vals:
                fixed[start:start + 8] = np.uint64(slot_vals[i]).tobytes()
            else:
                # payload bytes are copied verbatim, null or not
                raw = np.asarray(c.data)[r:r + 1]
                fixed[start:start + fx.column_sizes[i]] = raw.tobytes()
        # validity tail
        for i, c in enumerate(table.columns):
            valid = pyd[names[i]][r] is not None
            if valid:
                fixed[fx.validity_offset + i // 8] |= (1 << (i % 8))
        blob = bytes(fixed) + bytes(var)
        pad = (-len(blob)) % 8
        rows.append(blob + b"\0" * pad)
    offsets = np.cumsum([0] + [len(b) for b in rows]).astype(np.int32)
    return b"".join(rows), offsets


class TestVarRows:
    def test_round_trip(self, rng):
        t = _mixed_table(rng)
        blobs = convert.to_rows(t)
        assert len(blobs) == 1 and isinstance(blobs[0], VarRowBlob)
        back = convert.from_rows(blobs, [c.dtype for c in t.columns],
                                 names=list(t.names))
        assert_tables_equal(t, back)

    def test_round_trip_empty(self, rng):
        t = _mixed_table(rng, n=1).gather(np.zeros(0, np.int32))
        back = convert.from_rows(convert.to_rows(t),
                                 [c.dtype for c in t.columns],
                                 names=list(t.names))
        assert back.num_rows == 0

    def test_offsets_are_8_aligned(self, rng):
        t = _mixed_table(rng, n=64)
        blob = pack_var_rows(t)
        off = np.asarray(blob.offsets)
        assert (off % 8 == 0).all()
        assert off[0] == 0 and (np.diff(off) > 0).all()

    def test_golden_bytes(self, rng):
        t = _mixed_table(rng, n=37)
        blob = pack_var_rows(t)
        want, want_off = _oracle_bytes(t)
        got = blob.data.tobytes()[:len(want)]
        np.testing.assert_array_equal(np.asarray(blob.offsets), want_off)
        assert got == want

    def test_from_host_bytes(self, rng):
        t = _mixed_table(rng, n=50)
        blob = pack_var_rows(t)
        rt = VarRowBlob.from_host_bytes(blob.data, np.asarray(blob.offsets))
        back = unpack_var_rows(rt, [c.dtype for c in t.columns],
                               names=list(t.names))
        assert_tables_equal(t, back)

    def test_batching(self, rng):
        t = _mixed_table(rng, n=500)
        blobs = convert.to_rows(t, max_batch_bytes=8192)
        assert len(blobs) > 1
        assert all(b.nbytes <= 8192 for b in blobs)
        back = convert.from_rows(blobs, [c.dtype for c in t.columns],
                                 names=list(t.names))
        assert_tables_equal(t, back)

    def test_all_null_strings(self, rng):
        t = Table([
            ("s", Column.from_pylist([None, None, None], dt.STRING)),
            ("v", Column.from_numpy(np.arange(3, dtype=np.int64))),
        ])
        back = convert.from_rows(convert.to_rows(t), [dt.STRING, dt.INT64],
                                 names=["s", "v"])
        assert_tables_equal(t, back)

    def test_fixed_only_schema_rejected(self):
        with pytest.raises(ValueError, match="no variable-width"):
            compute_var_layout((dt.INT64, dt.INT32))

    def test_row_width_check_applies_to_fixed_part(self, rng):
        cols = [(f"c{i}", Column.from_numpy(np.zeros(4, np.int64)))
                for i in range(140)]                # fixed part > 1 KB
        cols.append(("s", Column.from_pylist(["a"] * 4, dt.STRING)))
        t = Table(cols)
        with pytest.raises(ValueError, match="row format limit"):
            convert.to_rows(t)
        blobs = convert.to_rows(t, check_row_width=False)
        back = convert.from_rows(blobs, [c.dtype for c in t.columns],
                                 names=list(t.names))
        assert_tables_equal(t, back)

    def test_program_cache_bucketed(self, rng):
        # Different batch sizes within one pow2 class share the jitted
        # programs (a stream of batches must not recompile per size).
        from spark_rapids_tpu.rows import varwidth as vw
        t1 = _mixed_table(rng, n=200)
        t2 = _mixed_table(rng, n=205)
        convert.from_rows(convert.to_rows(t1), [c.dtype for c in t1.columns])
        packs = vw._var_packer.cache_info().currsize
        unpacks = vw._var_unpacker.cache_info().currsize
        convert.from_rows(convert.to_rows(t2), [c.dtype for c in t2.columns])
        assert vw._var_packer.cache_info().currsize == packs
        # unpacker also keys on n (row count) which differs here; but char
        # buckets/word buckets must not add entries beyond that
        assert vw._var_unpacker.cache_info().currsize <= unpacks + 1


class TestChunkedCumsum:
    def test_matches_numpy(self, rng):
        from spark_rapids_tpu.ops.common import chunked_cumsum
        for n in (0, 1, 7, 62500, 62501, 200_003):
            x = rng.integers(-5, 9, n)
            got = np.asarray(chunked_cumsum(
                Column.from_numpy(x.astype(np.int64)).data))
            np.testing.assert_array_equal(got, np.cumsum(x))
