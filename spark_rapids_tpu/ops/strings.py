"""String column support: Arrow-style offsets + UTF-8 char buffer.

The reference punts on variable-width types (``CUDF_FAIL("Only fixed width
types are currently supported")`` — row_conversion.cu:515) but its capability
envelope includes cuDF's strings engine (SURVEY.md §2.3).  Representation:

  * ``data``    — ``uint8`` char buffer of all strings concatenated,
  * ``offsets`` — ``int32 (n+1,)``; string *i* is ``data[offsets[i]:offsets[i+1]]``,
  * ``validity``— bool mask as for fixed-width columns (null strings have
                  zero-length payloads).

Design note: per-element byte work is hostile to the VPU's 32-bit lanes, so
compute ops (contains/regex, in :func:`contains` and :mod:`regex`) operate on
the flat char buffer with vectorized comparisons + segment logic rather than
per-string loops.  Gather materializes the output size on host (eager op —
the engine's host-driven model, see :mod:`spark_rapids_tpu.ops`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dtypes import STRING
from ..column import Column


def strings_from_pylist(values: list[Optional[str]]) -> Column:
    """Build a STRING column from Python strings (``None`` = null)."""
    n = len(values)
    offsets = np.zeros(n + 1, dtype=np.int32)
    mask = np.ones(n, dtype=np.bool_)
    chunks: list[bytes] = []
    pos = 0
    for i, v in enumerate(values):
        if v is None:
            mask[i] = False
        else:
            b = v.encode("utf-8")
            chunks.append(b)
            pos += len(b)
        offsets[i + 1] = pos
    chars = np.frombuffer(b"".join(chunks), dtype=np.uint8).copy()
    validity = None if mask.all() else jnp.asarray(mask)
    return Column(data=jnp.asarray(chars), validity=validity,
                  offsets=jnp.asarray(offsets), dtype=STRING)


def strings_to_pylist(col: Column) -> list[Optional[str]]:
    chars = np.asarray(col.data, dtype=np.uint8)
    offsets = np.asarray(col.offsets)
    mask = None if col.validity is None else np.asarray(col.validity)
    out: list[Optional[str]] = []
    for i in range(len(offsets) - 1):
        if mask is not None and not mask[i]:
            out.append(None)
        else:
            out.append(bytes(chars[offsets[i]:offsets[i + 1]]).decode("utf-8"))
    return out


def strings_gather(col: Column, indices) -> Column:
    """Row gather for string columns.

    Eager: the output char-buffer size is data dependent, so it is synced to
    host once and the char copy runs as one vectorized device gather
    (position->source map built from searchsorted over the new offsets).
    """
    indices = jnp.asarray(indices)
    offsets = col.offsets
    starts = jnp.take(offsets, indices)
    lens = jnp.take(offsets, indices + 1) - starts
    new_offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(lens, dtype=jnp.int32)])
    total = int(new_offsets[-1])  # host sync: output size is data dependent
    if total == 0:
        chars = jnp.zeros(0, jnp.uint8)
    else:
        pos = jnp.arange(total, dtype=jnp.int32)
        row = jnp.searchsorted(new_offsets, pos, side="right") - 1
        src = jnp.take(starts, row) + (pos - jnp.take(new_offsets, row))
        chars = jnp.take(col.data, src)
    validity = None
    if col.validity is not None:
        validity = jnp.take(col.validity, indices)
    return Column(data=chars, validity=validity, offsets=new_offsets, dtype=STRING)
