"""Sharded streaming executor contracts (exec/dist_stream.py, driven on
the 8-virtual-device CPU mesh from conftest).

Oracle: a sharded stream must yield EXACTLY what the single-chip
``run_plan_stream`` yields over the same batches — per batch in
per-batch mode, as one table in combine mode — including with faults
injected at every dist site.  All aggregates here are integer-exact (or
derived from exact integer sums at finalize), so bit-identity holds
regardless of the psum merge order.

Design invariants under test beyond identity:

* one compiled program per (bucket, mesh) across the whole stream
  (``dist.compile_cache.miss`` == bucket count);
* ONE merge collective per group-by stream (``ici.collectives`` == 1);
* per-batch live-count host syncs are designed away (``host.sync.avoided``
  == batch count, total syncs below the per-batch ``run_plan_dist`` loop);
* overlap ratio > 0 on a feed with real decode latency.
"""

import json
import time

import numpy as np
import pytest

from spark_rapids_tpu import Column, Table
from spark_rapids_tpu.exec import (col, plan, run_plan_dist_stream,
                                   run_plan_stream)
from spark_rapids_tpu.obs import last_stream_metrics, registry
from spark_rapids_tpu.obs.query import bench_line
from spark_rapids_tpu.parallel import make_flat_mesh, shard_table
from spark_rapids_tpu.resilience import recovery_stats, reset_faults

#: 60/65/89 pad to a bucket; 64/88 sit exactly on per-shard capacity
#: boundaries at P=8 (caps 8,8,16,16,16,8 -> TWO distinct buckets).
SIZES = [60, 64, 65, 88, 89, 1]


@pytest.fixture(scope="module")
def mesh():
    return make_flat_mesh()


@pytest.fixture
def metrics_on(monkeypatch):
    monkeypatch.setenv("SRT_METRICS", "1")
    registry().reset()
    yield
    registry().reset()


@pytest.fixture
def faults(monkeypatch):
    monkeypatch.setenv("SRT_RETRY_BACKOFF", "0")
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()
    yield monkeypatch
    monkeypatch.delenv("SRT_FAULT", raising=False)
    reset_faults()


def _mk(n, seed, hi=3):
    """Nullable int key + bool key + nullable int values: every agg below
    is exact, so sharded results must be bit-identical, not just close."""
    r = np.random.default_rng(seed)
    return Table([
        ("k", Column.from_numpy(r.integers(0, hi, n).astype(np.int64),
                                validity=r.random(n) > 0.15)),
        ("b", Column.from_numpy(r.integers(0, 2, n).astype(np.bool_))),
        ("v", Column.from_numpy(r.integers(-100, 100, n).astype(np.int64),
                                validity=r.random(n) > 0.2)),
        ("w", Column.from_numpy(r.integers(0, 100, n).astype(np.int64))),
    ])


def _batches(sizes=SIZES):
    return [_mk(n, seed) for seed, n in enumerate(sizes)]


def _row_plan():
    return plan().filter(col("v") > 0).with_columns(d=col("v") * 2)


def _agg_plan():
    # mean over ints is exact too: finalize divides the exact sums.
    return (plan().filter(col("w") < 90)
            .groupby_agg(["k", "b"],
                         [("v", "sum", "sv"), ("v", "count", "cv"),
                          ("v", "min", "mn"), ("v", "max", "mx"),
                          ("v", "mean", "mv"), ("w", "count_all", "ca")],
                         domains={"k": (0, 2)}))


def _dicts(stream):
    return [t.to_pydict() for t in stream]


def _rowset(t: Table):
    cols = [t[n].to_pylist() for n in t.names]
    return sorted(zip(*cols), key=repr)


# ---------------------------------------------------------------------------
# 1. bit-identity vs the single-chip stream
# ---------------------------------------------------------------------------

class TestShardedStreamIdentity:
    def test_per_batch_bit_identical(self, mesh):
        p = _row_plan()
        want = _dicts(run_plan_stream(p, iter(_batches())))
        got = _dicts(run_plan_stream(p, iter(_batches()), mesh=mesh))
        assert got == want

    def test_per_batch_groupby_bit_identical(self, mesh):
        g = _agg_plan()
        want = _dicts(run_plan_stream(g, iter(_batches()), combine=False))
        got = _dicts(run_plan_stream(g, iter(_batches()), combine=False,
                                     mesh=mesh))
        assert got == want
        assert len(got) == len(SIZES)

    def test_combine_bit_identical(self, mesh):
        g = _agg_plan()
        want = _dicts(run_plan_stream(g, iter(_batches()), combine=True))
        got = _dicts(run_plan_dist_stream(g, iter(_batches()), mesh,
                                          combine=True))
        assert got == want
        assert len(got) == 1

    def test_empty_batches_mid_stream(self, mesh):
        batches = (_batches([60, 64])
                   + [_mk(0, 97)] + _batches([65]) + [_mk(0, 98)])
        for p, kw in ((_row_plan(), {}), (_agg_plan(), {"combine": True})):
            want = _dicts(run_plan_stream(
                p, iter(batches), **kw))
            got = _dicts(run_plan_stream(p, iter(batches), mesh=mesh, **kw))
            assert got == want

    def test_all_empty_stream(self, mesh):
        batches = [_mk(0, 1), _mk(0, 2)]
        for kw in ({}, {"combine": True}):
            want = _dicts(run_plan_stream(_agg_plan(), iter(batches), **kw))
            got = _dicts(run_plan_stream(_agg_plan(), iter(batches),
                                         mesh=mesh, **kw))
            assert got == want

    def test_combine_auto_falls_back_per_batch(self, mesh):
        # No domains hint and an int key -> no batch-invariant layout;
        # "auto" must replay every consumed batch through per-batch mode.
        g = plan().groupby_agg(["k"], [("v", "sum", "sv")])
        want = _dicts(run_plan_stream(g, iter(_batches()), combine=False,
                                      mesh=mesh))
        got = _dicts(run_plan_stream(g, iter(_batches()), combine="auto",
                                     mesh=mesh))
        assert got == want
        assert len(got) == len(SIZES)

    def test_combine_strict_raises_without_domains(self, mesh):
        g = plan().groupby_agg(["k"], [("v", "sum", "sv")])
        with pytest.raises(TypeError, match="static domain"):
            list(run_plan_stream(g, iter(_batches([60])), combine=True,
                                 mesh=mesh))

    def test_shuffled_join_streams_per_batch(self, mesh):
        r = np.random.default_rng(7)
        right = Table([
            ("rk", Column.from_numpy(
                r.integers(0, 3, 200).astype(np.int64))),
            ("rv", Column.from_numpy(
                r.integers(0, 40, 200).astype(np.int64))),
        ])
        p = plan().join_shuffled(right, left_on="k", right_on="rk")
        batches = _batches([60, 65])
        want = list(run_plan_stream(p, iter(batches)))
        got = list(run_plan_stream(p, iter(batches), mesh=mesh))
        assert len(got) == len(want)
        for w, g in zip(want, got):
            # The shuffle repartitions rows; compare as multisets.
            assert _rowset(g) == _rowset(w)

    def test_plan_run_dist_stream_method(self, mesh):
        g = _agg_plan()
        want = _dicts(run_plan_stream(g, iter(_batches([60, 65])),
                                      combine=True))
        got = _dicts(g.run_dist_stream(iter(_batches([60, 65])), mesh,
                                       combine=True))
        assert got == want


# ---------------------------------------------------------------------------
# 2. compile-once-per-(bucket, mesh) and the single merge collective
# ---------------------------------------------------------------------------

class TestShardedStreamCompile:
    def test_one_compile_per_bucket_per_batch(self, mesh, metrics_on):
        from spark_rapids_tpu.resilience.recovery import evict_device_caches
        evict_device_caches()
        registry().reset()
        list(run_plan_stream(_row_plan(), iter(_batches()), mesh=mesh))
        snap = registry().snapshot()
        # SIZES deal to per-shard caps {8, 16}: exactly two programs.
        assert snap.get("dist.compile_cache.miss", 0) == 2
        before_miss = snap["dist.compile_cache.miss"]
        list(run_plan_stream(_row_plan(), iter(_batches()), mesh=mesh))
        snap = registry().snapshot()
        assert snap["dist.compile_cache.miss"] == before_miss
        assert snap.get("dist.compile_cache.hit", 0) >= len(SIZES) - 2

    def test_one_merge_collective_per_combine_stream(self, mesh,
                                                     metrics_on):
        from spark_rapids_tpu.resilience.recovery import evict_device_caches
        evict_device_caches()
        registry().reset()
        out = _dicts(run_plan_dist_stream(_agg_plan(), iter(_batches()),
                                          mesh, combine=True))
        assert len(out) == 1
        qm = last_stream_metrics()
        assert qm.stream_merge_collectives == 1
        assert qm.stream_ici_bytes > 0
        snap = registry().snapshot()
        assert snap.get("ici.collectives", 0) == 1
        # two partial-aggregate buckets + the one merge program
        assert snap.get("dist.compile_cache.miss", 0) == 3

    def test_donation_recycles_shard_buffers(self, mesh, metrics_on):
        list(run_plan_stream(_row_plan(), iter(_batches()), mesh=mesh))
        qm = last_stream_metrics()
        # Row-shaped outputs alias the engine-owned shard copies: every
        # non-empty batch's dispatch reclaims its input HBM.
        assert qm.stream_donation_hits == len(SIZES)
        assert qm.stream_donation_misses == 0


# ---------------------------------------------------------------------------
# 3. host syncs: carried on device, paid once at stream end
# ---------------------------------------------------------------------------

class TestShardedStreamHostSyncs:
    def test_fewer_syncs_than_per_batch_dist_loop(self, mesh, metrics_on):
        from spark_rapids_tpu.exec.dist import run_plan_dist
        g = _agg_plan()
        registry().reset()
        for b in _batches():
            run_plan_dist(g, shard_table(b, mesh), mesh)
        loop_syncs = registry().snapshot().get("host.sync", 0)

        registry().reset()
        _dicts(run_plan_dist_stream(g, iter(_batches()), mesh,
                                    combine=True))
        snap = registry().snapshot()
        stream_syncs = snap.get("host.sync", 0)
        assert snap.get("host.sync.avoided", 0) == len(SIZES)
        assert stream_syncs < loop_syncs
        qm = last_stream_metrics()
        assert qm.stream_syncs_avoided == len(SIZES)
        assert qm.host_syncs == stream_syncs

    def test_per_batch_mode_also_avoids_live_count_syncs(self, mesh,
                                                         metrics_on):
        list(run_plan_stream(_row_plan(), iter(_batches()), mesh=mesh))
        snap = registry().snapshot()
        assert snap.get("host.sync.avoided", 0) == len(SIZES)
        assert snap.get("host.sync.avoided.dist.live_count", 0) \
            == len(SIZES)


# ---------------------------------------------------------------------------
# 4. overlap: the sharded pipeline still beats the serial phase sum
# ---------------------------------------------------------------------------

class TestShardedStreamOverlap:
    def test_overlap_ratio_positive_with_slow_feed(self, mesh):
        def slow_feed():
            for seed, n in enumerate([80] * 6):
                time.sleep(0.02)        # simulated decode latency
                yield _mk(n, seed)

        outs = list(run_plan_stream(_row_plan(), slow_feed(), mesh=mesh,
                                    inflight=3, prefetch=4))
        assert len(outs) == 6
        qm = last_stream_metrics()
        assert qm.stream_overlap_ratio > 0
        assert qm.total_seconds < qm.stream_serial_seconds
        assert qm.stream_shards == mesh.devices.size


# ---------------------------------------------------------------------------
# 5. observability and knobs
# ---------------------------------------------------------------------------

class TestShardedStreamObservability:
    def test_query_metrics_dist_stream_block(self, mesh, metrics_on):
        _dicts(run_plan_dist_stream(_agg_plan(), iter(_batches()), mesh,
                                    combine=True))
        payload = json.loads(last_stream_metrics().to_json())
        assert payload["mode"] == "dist_stream"
        assert payload["schema_version"] == 11
        s = payload["stream"]
        assert s["shards"] == 8
        assert s["merge_collectives"] == 1
        assert s["ici_bytes"] > 0
        assert s["syncs_avoided"] == len(SIZES)
        assert s["batches"] == len(SIZES)
        # cost ledger composes: the merge collective's wall shows as ici
        assert payload["cost"]["ici_seconds"] > 0

    def test_bench_dist_stream_line(self, mesh, metrics_on):
        _dicts(run_plan_dist_stream(_agg_plan(), iter(_batches()), mesh,
                                    combine=True))
        payload = json.loads(bench_line("dist_stream"))
        assert payload["metric"] == "dist_stream"
        assert payload["runs"] == 1
        assert payload["shards"] == 8
        assert payload["batches"] == len(SIZES)
        assert payload["merge_collectives"] == 1
        assert payload["ici_bytes"] > 0
        assert payload["syncs_avoided"] == len(SIZES)

    def test_mesh_arg_validated_jax_free(self):
        with pytest.raises(ValueError, match="mesh must be a jax Mesh"):
            run_plan_stream(_row_plan(), iter([]), mesh=object())
        with pytest.raises(ValueError, match="requires a mesh"):
            run_plan_dist_stream(_row_plan(), iter([]), None)

    def test_dist_stream_inflight_knob(self, monkeypatch):
        from spark_rapids_tpu.config import (dist_stream_inflight,
                                             stream_inflight)
        monkeypatch.delenv("SRT_DIST_STREAM_INFLIGHT", raising=False)
        assert dist_stream_inflight() == stream_inflight()
        monkeypatch.setenv("SRT_DIST_STREAM_INFLIGHT", "5")
        assert dist_stream_inflight() == 5
        monkeypatch.setenv("SRT_DIST_STREAM_INFLIGHT", "0")
        with pytest.raises(ValueError, match="SRT_DIST_STREAM_INFLIGHT"):
            dist_stream_inflight()

    def test_shard_capacity_schedule(self):
        # jax-free schedule math: snapped to the shared geometric ladder
        # with the dist floor of 8, shared across same-bucket sizes.
        from spark_rapids_tpu.exec.bucketing import shard_capacity
        caps = [shard_capacity(n, 8) for n in SIZES]
        assert caps == [8, 8, 16, 16, 16, 8]
        assert len(set(caps)) == 2
        with pytest.raises(ValueError, match="shards"):
            shard_capacity(64, 0)


# ---------------------------------------------------------------------------
# faulted-dist-stream CI lane (ci/premerge-build.sh arms a shard-targeted
# mid-stream OOM; the tests pin their own specs so they pass standalone)
# ---------------------------------------------------------------------------

@pytest.mark.faulted_dist_stream
class TestFaultedShardedStream:
    def _golden_then_faulted(self, faults, p, spec, mesh, **kw):
        reset_faults()
        want = _dicts(run_plan_stream(p, iter(_batches()), mesh=mesh, **kw))
        faults.setenv("SRT_FAULT", spec)
        reset_faults()
        before = recovery_stats().snapshot()
        got = _dicts(run_plan_stream(p, iter(_batches()), mesh=mesh, **kw))
        assert got == want, spec
        assert recovery_stats().delta(before)["dist_retries"] >= 1, spec

    def test_per_batch_dist_dispatch_fault(self, faults, mesh):
        self._golden_then_faulted(
            faults, _row_plan(), "oom:dist-dispatch:2:shard=3", mesh)

    def test_per_batch_collective_fault(self, faults, mesh):
        self._golden_then_faulted(
            faults, _agg_plan(), "oom:collective:2:shard=5", mesh,
            combine=False)

    def test_combine_dist_dispatch_fault(self, faults, mesh):
        self._golden_then_faulted(
            faults, _agg_plan(), "oom:dist-dispatch:2:shard=2", mesh,
            combine=True)
        assert last_stream_metrics().stream_merge_collectives == 1

    def test_combine_merge_collective_fault(self, faults, mesh):
        self._golden_then_faulted(
            faults, _agg_plan(), "oom:collective:2", mesh, combine=True)

    def test_collect_fault_mid_drain(self, faults, mesh):
        self._golden_then_faulted(
            faults, _row_plan(), "oom:collect:1", mesh)

    def test_shuffle_fault_in_streamed_join(self, faults, mesh):
        r = np.random.default_rng(11)
        right = Table([
            ("rk", Column.from_numpy(
                r.integers(0, 3, 150).astype(np.int64))),
            ("rv", Column.from_numpy(
                r.integers(0, 9, 150).astype(np.int64))),
        ])
        p = plan().join_shuffled(right, left_on="k", right_on="rk")
        batches = _batches([60, 65])
        reset_faults()
        want = [_rowset(t) for t in
                run_plan_stream(p, iter(batches), mesh=mesh)]
        faults.setenv("SRT_FAULT", "oom:shuffle:1:shard=2")
        reset_faults()
        before = recovery_stats().snapshot()
        got = [_rowset(t) for t in
               run_plan_stream(p, iter(batches), mesh=mesh)]
        assert got == want
        assert recovery_stats().delta(before)["dist_retries"] >= 1

    def test_dist_stall_raises_not_hangs(self, faults, mesh):
        from spark_rapids_tpu.resilience import DistStallError
        faults.setenv("SRT_DIST_TIMEOUT", "0.2")
        faults.setenv("SRT_FAULT", "stall:dist-dispatch:1:shard=4")
        reset_faults()
        with pytest.raises(DistStallError):
            _dicts(run_plan_stream(_row_plan(), iter(_batches([60])),
                                   mesh=mesh))
