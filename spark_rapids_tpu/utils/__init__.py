"""Utilities: tracing/profiling scopes and device-memory management."""

from .memory import (MemoryScope, device_memory_stats, donating_jit, free,
                     no_implicit_transfers)
from .tracing import start_server, trace, traced

__all__ = [
    "MemoryScope",
    "device_memory_stats",
    "donating_jit",
    "free",
    "no_implicit_transfers",
    "start_server",
    "trace",
    "traced",
]
