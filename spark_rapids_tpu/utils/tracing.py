"""Named profiler scopes — the NVTX-ranges analog.

The reference's tracing story is NVTX ranges in the cudf Java layer behind
``-Dai.rapids.cudf.nvtx.enabled`` (pom.xml:84, :366-369) plus ``-lineinfo``
device compiles for profiler introspection (ConfigureCUDA.cmake:33-37).  The
TPU equivalents are ``jax.profiler`` trace annotations (visible in
TensorBoard/XPlane captures and Perfetto) and jitted-function naming.

Everything here is a no-op unless ``SRT_TRACE=1`` (config.trace_enabled), so
instrumented code pays nothing in production — the same opt-in contract as
the NVTX toggle.

:func:`trace` has two further, jax-free backends: when the structured
span timeline is recording (``SRT_TRACE_TIMELINE=1`` or an active
``obs.timeline.recording()`` scope) every trace scope is also recorded
as a timeline span under category ``"trace"``, and when metrics are on
(``SRT_METRICS=1``) every scope lands in the per-query flight-recorder
ring (obs/flight.py) that postmortem bundles drain — the same
instrumentation points feed the profiler, the Chrome-trace export, and
the black box.  With jax profiling off, no jax import happens.

Usage::

    with trace("convert_to_rows"):
        ...
    @traced
    def shuffle(...): ...

``start_server(port)`` re-exports the on-demand profiler server so a running
job can be attached to (the TPU replacement for attaching nsys to a live
process).
"""

from __future__ import annotations

import functools
from typing import Callable, TypeVar

from ..config import trace_enabled

_F = TypeVar("_F", bound=Callable)


class _NullScope:
    """Shared disabled-tracing context (no generator machinery on the
    cold path — instrumented hot loops enter/exit two empty methods)."""
    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc) -> None:
        return None


_NULL_SCOPE = _NullScope()


class _ComboScope:
    """Several backends at once: timeline span, flight-recorder span,
    jax profiler annotation — whichever subset is live."""
    __slots__ = ("_scopes",)

    def __init__(self, *scopes):
        self._scopes = scopes

    def __enter__(self):
        for s in self._scopes:
            s.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        for s in reversed(self._scopes):
            s.__exit__(*exc)
        return None


def _obs_span(name: str, attrs: dict):
    """The jax-free backends' span for this scope, or None when both are
    off.  ``timeline.span`` is the ONE producer: it records a timeline
    span when the recorder is on and otherwise hands back a
    flight-recorder scope when metrics are on (obs/flight.py), so this
    one call covers both sinks without double-recording.  Avoids
    importing ``obs`` unless the timeline module is already loaded or an
    env flag asks for it — a cold ``import spark_rapids_tpu`` must not
    pull in the obs subsystem."""
    import sys
    tl = sys.modules.get("spark_rapids_tpu.obs.timeline")
    if tl is None:
        from ..config import metrics_enabled, timeline_enabled
        if not (timeline_enabled() or metrics_enabled()):
            return None
        from ..obs import timeline as tl
    s = tl.span(name, cat="trace", **attrs)
    return None if s is tl.NULL_SPAN else s


def trace(name: str, **attrs):
    """Named scope visible in jax profiler captures (NVTX push/pop
    analog), in the Chrome-trace export when the span timeline is
    recording, and in the per-query flight-recorder ring when metrics
    are on (``SRT_METRICS=1``, obs/flight.py).

    ``attrs`` pass through as annotation metadata (profiler-visible metric
    labels, e.g. ``trace("shuffle", partitions=8)``).  When every backend
    is off this returns a shared null context: no profiler import, no
    annotation construction, no attr formatting."""
    obs_span = _obs_span(name, attrs)
    if not trace_enabled():
        return obs_span if obs_span is not None else _NULL_SCOPE
    import jax.profiler
    ann = jax.profiler.TraceAnnotation(name, **attrs)
    if obs_span is None:
        return ann
    return _ComboScope(obs_span, ann)


def traced(fn: _F) -> _F:
    """Decorator form of :func:`trace`, scope named after the function
    (name computed once at decoration time; the disabled path is a single
    flag check before a plain call — no contextmanager entry)."""
    name = f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        scope = trace(name)
        if scope is _NULL_SCOPE:
            return fn(*args, **kwargs)
        with scope:
            return fn(*args, **kwargs)

    return wrapper  # type: ignore[return-value]


def start_server(port: int = 9012):
    """Start the on-demand jax profiler server (attach via TensorBoard).

    Host-only tooling gets a clear failure instead of an opaque deep
    ImportError when jax is absent, and an explicit ``SRT_TRACE=0`` is
    honored — a process whose operator disabled tracing refuses to open a
    profiling port rather than silently overriding the knob.
    """
    import os
    raw = os.environ.get("SRT_TRACE")
    if raw is not None and not trace_enabled():
        raise RuntimeError(
            f"start_server refused: SRT_TRACE={raw!r} disables tracing "
            f"for this process (unset it or set SRT_TRACE=1 to profile)")
    try:
        import jax.profiler
    except ImportError as e:
        raise RuntimeError(
            "start_server requires jax (jax.profiler provides the "
            "profiling server); this host-only environment has no jax — "
            "install the jax stack or capture a structured timeline "
            "instead (SRT_TRACE_TIMELINE=1, obs/timeline.py)") from e
    return jax.profiler.start_server(port)
