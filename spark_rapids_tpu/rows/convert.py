"""Columnar ↔ row-major conversion (the reference's flagship feature).

TPU-native equivalent of ``spark_rapids_jni::convert_to_rows`` /
``convert_from_rows`` (reference: row_conversion.cu:458-517, :519-575 and the
Java API RowConversion.java:101-121).  The device payload is the word-major
uint32 row image of :mod:`.image` (see its module doc for why a device-side
flat byte blob is wrong on TPU); the exact Spark-row **bytes** — the interop
contract — are materialized at the host boundary via :meth:`RowBlob.data` /
:meth:`RowBlob.from_host_bytes`.

Semantics preserved from the reference:

  * output split into multiple row blobs so no blob exceeds 2**31 bytes, with
    batch row counts in multiples of 32 (row_conversion.cu:476-479, :505-511),
  * 1 KB row-width limit (RowConversion.java:98-99) — liftable here since TPU
    has no shared-memory constraint (``check_row_width=False``),
  * ``from_rows`` validates blob size against the schema layout
    (row_conversion.cu:541: "The layout of the data appears to be off"),
  * null rows' payload bytes are copied verbatim (the engine never invents
    values), and — unlike the reference, which leaves pad/garbage bits —
    padding bytes and unused validity bits are deterministically zero.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import DType
from ..table import Table
from .image import (host_bytes_to_words, pack_image, unpack_image,
                    words_to_host_bytes)
from .layout import (BATCH_ROW_MULTIPLE, MAX_BATCH_BYTES, MAX_ROW_WIDTH,
                     RowLayout, compute_fixed_width_layout)


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class RowBlob:
    """A batch of rows serialized to the fixed-width row format.

    Equivalent of the reference's ``LIST<INT8>`` output column
    (row_conversion.cu:405-406), held device-side as the word-major
    ``(row_size/4, num_rows)`` uint32 image.  ``data`` materializes the
    byte-exact host blob; ``offsets`` is the int32 ``(n+1,)`` row-offset
    sequence of the reference contract.
    """

    words: jax.Array       # uint32 (row_size // 4, num_rows)
    row_size: int          # static

    def tree_flatten(self):
        return (self.words,), self.row_size

    @classmethod
    def tree_unflatten(cls, row_size, children):
        (words,) = children
        return cls(words=words, row_size=row_size)

    @property
    def num_rows(self) -> int:
        return int(self.words.shape[1])

    @property
    def nbytes(self) -> int:
        return self.num_rows * self.row_size

    @property
    def data(self) -> np.ndarray:
        """Byte-exact host row blob (the Spark ``UnsafeRow`` interop bytes)."""
        return words_to_host_bytes(self.words, self.row_size)

    @property
    def offsets(self) -> jax.Array:
        return jnp.arange(self.num_rows + 1, dtype=jnp.int32) * self.row_size

    @classmethod
    def from_host_bytes(cls, data: np.ndarray, row_size: int) -> "RowBlob":
        """Build a device blob from exact host row bytes (the inverse interop
        direction: Spark rows arriving over the wire)."""
        arr = np.asarray(data)
        if arr.dtype not in (np.uint8, np.int8):
            raise ValueError("Only a list of bytes is supported as input")
        words = host_bytes_to_words(arr.view(np.uint8), row_size)
        return cls(words=jnp.asarray(words), row_size=row_size)


# -- jitted kernels, cached per schema ---------------------------------------

@functools.lru_cache(maxsize=None)
def _packer(schema: tuple[DType, ...]):
    layout = compute_fixed_width_layout(schema)

    @jax.jit
    def pack(datas: tuple[jax.Array, ...], masks: tuple[jax.Array, ...]) -> jax.Array:
        return pack_image(layout, datas, masks)

    return layout, pack


@functools.lru_cache(maxsize=None)
def _unpacker(schema: tuple[DType, ...]):
    layout = compute_fixed_width_layout(schema)

    @jax.jit
    def unpack(words: jax.Array):
        return unpack_image(layout, words)

    return layout, unpack


# -- public API ---------------------------------------------------------------

def to_rows(table: Table, *, max_batch_bytes: int = MAX_BATCH_BYTES,
            check_row_width: bool = True) -> list:
    """Convert a table to row blobs.

    Fixed-width schemas produce :class:`RowBlob`\\ s; schemas with string
    columns produce :class:`.varwidth.VarRowBlob`\\ s (beyond the
    reference, which fails on variable width — row_conversion.cu:514-516).
    Returns one blob per batch; multiple blobs only when the total byte
    size would exceed ``max_batch_bytes`` (reference contract:
    RowConversion.java:32-48).
    """
    from ..config import ensure_compile_cache
    ensure_compile_cache()
    schema = tuple(table.schema())
    if any(dt.is_string or dt.is_nested for dt in schema):
        from .varwidth import compute_var_layout, to_var_rows
        if check_row_width:
            fixed_size = compute_var_layout(schema).fixed.row_size
            if fixed_size > MAX_ROW_WIDTH:
                raise ValueError(
                    f"Fixed row part {fixed_size} exceeds the "
                    f"{MAX_ROW_WIDTH}-byte row format limit (pass "
                    f"check_row_width=False to lift; the variable section "
                    f"is exempt — rows are unbounded by design there)")
        return to_var_rows(table, max_batch_bytes=max_batch_bytes)
    layout, pack = _packer(schema)
    if check_row_width and layout.row_size > MAX_ROW_WIDTH:
        raise ValueError(
            f"Row size {layout.row_size} exceeds the {MAX_ROW_WIDTH}-byte row "
            f"format limit (pass check_row_width=False to lift)")

    num_rows = table.num_rows
    max_rows = layout.max_rows_per_batch(max_batch_bytes)
    if max_rows <= 0:
        raise ValueError("row size too large for the batch byte limit")

    def batch_blob(start: int, count: int) -> RowBlob:
        datas = tuple(c.data[start:start + count] for c in table.columns)
        masks = tuple(
            jnp.ones(count, jnp.bool_) if c.validity is None
            else c.validity[start:start + count]
            for c in table.columns)
        if count == 0:
            words = jnp.zeros((layout.row_size // 4, 0), jnp.uint32)
        else:
            words = pack(datas, masks)
        return RowBlob(words=words, row_size=layout.row_size)

    if num_rows == 0:   # one empty blob so the round trip stays total
        return [batch_blob(0, 0)]
    return [batch_blob(start, min(max_rows, num_rows - start))
            for start in range(0, num_rows, max_rows)]


def from_rows(blobs: Union[Sequence[RowBlob], RowBlob], schema: Sequence[DType],
              names: Optional[Sequence[str]] = None) -> Table:
    """Convert row blobs back to a columnar table.

    ``schema`` describes the columns to extract (the caller records it at
    ``to_rows`` time, as in RowConversionTest.java:46-49).  Multiple blobs are
    concatenated in order (the reference's batched-output inverse).
    """
    from ..config import ensure_compile_cache
    ensure_compile_cache()
    from .varwidth import VarRowBlob, unpack_var_rows
    if isinstance(blobs, (RowBlob, VarRowBlob)):
        blobs = [blobs]
    schema = tuple(schema)
    if names is None:
        names = [f"c{i}" for i in range(len(schema))]
    elif len(names) != len(schema):
        raise ValueError(f"{len(names)} names for {len(schema)} schema columns")
    if any(dt.is_string or dt.is_nested for dt in schema):
        from ..ops.common import concat_tables
        from .varwidth import empty_var_table
        if not blobs:
            return empty_var_table(schema, names)
        parts = [unpack_var_rows(b, schema, names) for b in blobs]
        return parts[0] if len(parts) == 1 else concat_tables(parts)
    layout, unpack = _unpacker(schema)
    W = layout.row_size // 4
    if not blobs:
        blobs = [RowBlob(words=jnp.zeros((W, 0), jnp.uint32),
                         row_size=layout.row_size)]

    all_datas: list[tuple] = []
    all_valid: list[tuple] = []
    for blob in blobs:
        if blob.words.dtype != jnp.uint32:
            raise ValueError("Only a word image of bytes is supported as input")
        if blob.row_size != layout.row_size or blob.words.shape[0] != W:
            raise ValueError("The layout of the data appears to be off")
        if blob.num_rows == 0:
            all_datas.append(tuple(jnp.zeros(0, dt.jnp_dtype) for dt in schema))
            all_valid.append(tuple(jnp.zeros(0, jnp.bool_) for _ in schema))
            continue
        datas, valid = unpack(blob.words)
        all_datas.append(datas)
        all_valid.append(valid)

    if len(all_datas) > 1:
        datas = tuple(jnp.concatenate([d[i] for d in all_datas])
                      for i in range(len(schema)))
        valid = tuple(jnp.concatenate([v[i] for v in all_valid])
                      for i in range(len(schema)))
    else:
        datas, valid = all_datas[0], all_valid[0]

    columns = []
    for i, (name, dtype) in enumerate(zip(names, schema)):
        columns.append((name, Column(data=datas[i], validity=valid[i], dtype=dtype)))
    return Table(columns)
