"""Broadcast join inside compiled plans.

The TPU re-architecture of the Spark broadcast hash join (probe side
streams, build side is small and replicated).  A hash table is the wrong
tool on TPU — random scatters to build, random gathers to probe; instead
the binder turns the build side into one of two probe structures, chosen
statically at bind time and cached per build-key buffer identity:

* **direct** — build keys span a small static range: an int32 slot array
  of size (hi-lo+1) maps key-lo → build row (-1 = absent).  Probing is a
  single vectorized gather; O(1) per probe row, no hashing.
* **search** — general integer keys: the build keys are pre-sorted and the
  probe runs a vectorized binary search (``jnp.searchsorted``, log2(D)
  small-table gathers).

Composite (multi-column) keys are **bit-packed** into one int64 probe
word at bind time: each key contributes ``ceil(log2(span+1))`` bits at a
static shift, derived from the build side's value ranges — the probe side
computes the same packing in-program and out-of-range values can never
alias (they fail the per-key range mask first).

Both probes run sync-free inside the plan program.  Build keys must be
unique (dimension-table contract — checked at bind); many-to-many joins
with data-dependent expansion stay in the eager layer (ops.join, which
the reference's cuDF hash join envelope maps to).

Null semantics: a null in ANY probe or build key column means the row
never matches (Spark/cuDF equi-join); a left join nulls the build
payloads of unmatched rows, inner/semi drop them via the selection mask,
anti keeps exactly them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..column import Column
from ..dtypes import INT32, INT64
from .plan import JoinStep

#: Max slot-array cells for the direct probe (int32 => 16 MB at the cap).
DIRECT_PROBE_MAX = 1 << 22

#: Max total bits for a packed composite key (int64, sign bit spared).
MAX_PACKED_BITS = 62


@dataclass(frozen=True)
class JoinKeyMeta:
    """One column of a (possibly composite) join key."""
    probe_name: str
    lo: int                              # build-side min (valid rows)
    hi: int                              # build-side max
    shift: int                           # bit position in the packed word
    type_id: int                         # probe dtype must match exactly
    scale: int


@dataclass(frozen=True)
class JoinMeta:
    """Static join description (part of the compile-cache key)."""
    index: int
    how: str
    keys: tuple[JoinKeyMeta, ...]
    mode: str                            # "direct" | "search"
    packed_hi: int                       # max packed key value
    dim_rows: int
    #: build rows where every key column is non-null (0 => no matches)
    valid_keys: int
    #: fixed-width build payloads: (side-input name, output name)
    pays: tuple[tuple[str, str], ...]
    #: string build payloads: (build column name, output name)
    str_pays: tuple[tuple[str, str], ...]
    #: hidden state column carrying matched build row ids (None when no
    #: string payloads need late gathering)
    rowid_name: Optional[str]


# probe-structure cache: build key column buffers -> (key metas sans
# probe names, mode, packed_hi, arrays)
_PROBE_CACHE: dict = {}


def _build_probe(key_cols: list[Column], dedupe: bool = False):
    """(per-key (lo, hi, shift), mode, packed_hi, side arrays); cached per
    build key buffer identities.  ``dedupe`` drops duplicate build keys
    (keeping an arbitrary row per key) — sound only for membership joins
    (semi/anti), where no payload rides the match."""
    from .stats import _guarded_cache_get, _guarded_cache_put
    buffers = tuple(b for c in key_cols
                    for b in (c.data, c.validity) if b is not None)
    cache_key = (dedupe,) + tuple(id(b) for b in buffers)
    hit = _guarded_cache_get(_PROBE_CACHE, cache_key, buffers)
    if hit is not None:
        return hit

    n = key_cols[0].size
    valid = np.ones(n, np.bool_)
    for c in key_cols:
        if c.validity is not None:
            valid &= np.asarray(c.validity)
    rows = np.arange(n, dtype=np.int32)[valid]
    np_keys = [np.asarray(c.data)[valid] for c in key_cols]
    from ..utils.memory import record_host_sync
    record_host_sync("join.build_probe",
                     sum(c.data.nbytes for c in key_cols))

    if rows.size == 0:
        result = ((tuple((0, 0, 0) for _ in key_cols)), "search", 0, 0,
                  {"keys": jnp.zeros(0, jnp.int64),
                   "rows": jnp.zeros(0, jnp.int32)})
        _guarded_cache_put(_PROBE_CACHE, cache_key, buffers, result)
        return result

    los = [int(k.min()) for k in np_keys]
    his = [int(k.max()) for k in np_keys]
    bits = [max(int(hi - lo).bit_length(), 1)
            for lo, hi in zip(los, his)]
    if sum(bits) > MAX_PACKED_BITS:
        raise ValueError(
            f"composite join key needs {sum(bits)} bits packed "
            f"(> {MAX_PACKED_BITS}); use the eager ops.join")
    shifts = []
    at = 0
    for b in reversed(bits):             # last key = least significant
        shifts.append(at)
        at += b
    shifts = list(reversed(shifts))

    packed = np.zeros(rows.size, np.int64)
    for k, lo, sh in zip(np_keys, los, shifts):
        packed |= (k.astype(np.int64) - lo) << sh
    was_unique = True
    if dedupe:
        uniq, first = np.unique(packed, return_index=True)
        was_unique = uniq.size == packed.size
        packed, rows = uniq, rows[first]
    elif np.unique(packed).size != packed.size:
        raise ValueError(
            "broadcast join requires unique build-side keys "
            "(use the eager ops.join for many-to-many joins, or a "
            "semi/anti join for membership tests)")
    packed_hi = int(packed.max())

    if packed_hi + 1 <= DIRECT_PROBE_MAX:
        lookup = np.full(packed_hi + 1, -1, np.int32)
        lookup[packed] = rows
        arrays = {"lookup": jnp.asarray(lookup)}
        mode = "direct"
    else:
        order = np.argsort(packed, kind="stable")
        arrays = {"keys": jnp.asarray(packed[order]),
                  "rows": jnp.asarray(rows[order])}
        mode = "search"
    result = (tuple(zip(los, his, shifts)), mode, packed_hi,
              int(rows.size), arrays)
    _guarded_cache_put(_PROBE_CACHE, cache_key, buffers, result)
    if was_unique:
        # Unique build keys make the deduped and plain probe structures
        # identical — store under both cache keys so a dimension probed
        # by an inner and a semi join in the same bank builds one probe.
        other = ((not dedupe,) + cache_key[1:])
        _guarded_cache_put(_PROBE_CACHE, other, buffers, result)
    return result


def bind_join(bound, step: JoinStep, index: int,
              current_names: list[str]) -> JoinMeta:
    """Register side inputs on ``bound`` and produce the static meta."""
    dim = step.table
    key_cols = []
    for ln, rn in zip(step.left_on, step.right_on):
        if ln in bound.string_cols or ln in bound.dictionaries:
            raise TypeError(
                f"broadcast join probe key {ln!r} is a string column; "
                f"dictionary-encode both sides or use the eager ops.join")
        if rn not in dim:
            raise KeyError(f"build-side key {rn!r} not in "
                           f"{list(dim.names)}")
        c = dim[rn]
        if (c.offsets is not None or c.dtype.is_floating
                or c.dtype.is_nested):
            raise TypeError(
                f"broadcast join keys must be integer-typed "
                f"({rn!r} is {c.dtype.type_id.name}); "
                f"dictionary-encode strings or use the eager ops.join")
        key_cols.append(c)

    spans, mode, packed_hi, valid_keys, arrays = _build_probe(
        key_cols, dedupe=step.how in ("semi", "anti"))
    prefix = f"__join{index}__"
    for nm, arr in arrays.items():
        bound.side_inputs[prefix + nm] = Column(
            data=arr, dtype=INT32 if arr.dtype == jnp.int32 else INT64)

    key_metas = tuple(
        JoinKeyMeta(ln, lo, hi, sh, int(c.dtype.type_id), c.dtype.scale)
        for ln, c, (lo, hi, sh) in zip(step.left_on, key_cols, spans))

    right_keys = set(step.right_on)
    pays: list[tuple[str, str]] = []
    str_pays: list[tuple[str, str]] = []
    rowid_name = None
    if step.how in ("inner", "left"):
        for name, c in dim.items():
            if name in right_keys:
                continue
            if name in current_names:
                raise ValueError(
                    f"join output column {name!r} collides with an existing "
                    f"column; rename one side first")
            if c.dtype is not None and c.dtype.is_nested:
                raise TypeError(
                    f"nested build-side payload {name!r} "
                    f"({c.dtype.type_id.name}) is not supported in compiled "
                    f"plans; drop it from the build table or use the eager "
                    f"ops.join")
            if c.offsets is None:
                side_name = prefix + "pay__" + name
                bound.side_inputs[side_name] = c
                pays.append((side_name, name))
            else:
                str_pays.append((name, name))
        if str_pays:
            rowid_name = prefix + "rowid"
            bound.join_string_srcs[rowid_name] = [
                (dim[src], out) for src, out in str_pays]

    return JoinMeta(index, step.how, key_metas, mode, packed_hi,
                    dim.num_rows, valid_keys, tuple(pays), tuple(str_pays),
                    rowid_name)


def trace_join(cols, sel, side, meta: JoinMeta):
    """Traced probe + payload attach (runs inside the plan program)."""
    n = next(iter(cols.values())).size
    packed = jnp.zeros(n, jnp.int64)
    in_range = jnp.ones(n, jnp.bool_)
    for km in meta.keys:
        k = cols[km.probe_name]
        if (int(k.dtype.type_id) != km.type_id
                or k.dtype.scale != km.scale):
            raise TypeError(
                f"join key dtype mismatch: probe {km.probe_name!r} is "
                f"{k.dtype!r}, build key type id is {km.type_id} "
                f"(cast first)")
        kd = k.data
        ok = (kd >= jnp.asarray(km.lo, kd.dtype)) & \
             (kd <= jnp.asarray(km.hi, kd.dtype))
        if k.validity is not None:
            ok = ok & k.validity
        in_range = in_range & ok
        part = (jnp.clip(kd, jnp.asarray(km.lo, kd.dtype),
                         jnp.asarray(km.hi, kd.dtype)).astype(jnp.int64)
                - km.lo) << km.shift
        packed = packed | part
    prefix = f"__join{meta.index}__"

    if meta.valid_keys == 0:
        dimrow = jnp.zeros(n, jnp.int32)
        found = jnp.zeros(n, jnp.bool_)
    elif meta.mode == "direct":
        lookup = side[prefix + "lookup"].data
        slot = jnp.clip(packed, 0, meta.packed_hi).astype(jnp.int32)
        dimrow = jnp.take(lookup, slot)
        # per-key in-range probes can still PACK above the max observed
        # build packing; without this guard the clip would collapse them
        # onto the build row holding the max packed key
        found = in_range & (packed <= meta.packed_hi) & (dimrow >= 0)
    else:
        skeys = side[prefix + "keys"].data
        srows = side[prefix + "rows"].data
        d = skeys.shape[0]
        pos = jnp.clip(jnp.searchsorted(skeys, packed).astype(jnp.int32),
                       0, d - 1)
        found = in_range & (jnp.take(skeys, pos) == packed)
        dimrow = jnp.take(srows, pos)
    dimrow = jnp.clip(dimrow, 0, max(meta.dim_rows - 1, 0))

    if meta.how == "semi":
        return cols, found if sel is None else (sel & found)
    if meta.how == "anti":
        return cols, (~found) if sel is None else (sel & ~found)

    new = dict(cols)
    for side_name, out_name in meta.pays:
        pay = side[side_name]
        if meta.dim_rows == 0:
            # Empty build side (a dimension filter matched nothing): no
            # probe row is `found`, so payload values never surface —
            # but the gather itself must not read an empty axis.
            from ..column import all_null_column
            new[out_name] = all_null_column(pay.dtype, n)
            continue
        data = jnp.take(pay.data, dimrow, axis=0)
        validity = (None if pay.validity is None
                    else jnp.take(pay.validity, dimrow))
        if meta.how == "left":
            validity = found if validity is None else (validity & found)
        new[out_name] = Column(data=data, validity=validity, dtype=pay.dtype)
    if meta.rowid_name is not None:
        new[meta.rowid_name] = Column(data=dimrow, validity=found,
                                      dtype=INT32)
    if meta.how == "inner":
        sel = found if sel is None else (sel & found)
    return new, sel


# ---------------------------------------------------------------------------
# shuffled (big-big) join — many-to-many expansion inside the program
# ---------------------------------------------------------------------------
#
# The broadcast join above requires unique build keys and a small build
# side.  TPC-DS q95 joins two *fact* tables (web_sales x web_sales on
# order number): no side broadcasts, keys repeat, and the output size is a
# data-dependent many-to-many expansion.  The reference envelope serves
# this with cuDF's shuffled hash join (both sides repartitioned, then a
# per-partition hash join).  The TPU re-architecture:
#
# * the probe — factorize both sides' keys over their union with ONE
#   multi-key sort, then a vectorized searchsorted (ops.join's fused
#   kernel) — runs at BIND time and is cached per (left keys, right
#   table) buffer identity.  Its outputs (per-left-row match count, match
#   range start, right-row order) depend only on the two key multisets,
#   never on the plan's filters, so repeated queries over the same tables
#   skip the sort entirely;
# * the capacity — a pow2 bucket of the unfiltered match total — is
#   static; a filter can only shrink the live expansion, so the program
#   writes into a fixed (capacity,)-shaped output with a selection mask
#   (padded slots dead), keeping the whole plan one XLA program;
# * the in-program expansion recovers each output slot's owning left row
#   with the scatter-indicator + prefix-sum trick (O(capacity), no
#   searchsorted over the output).

@dataclass(frozen=True)
class ShuffledJoinMeta:
    """Static description of one shuffled join (compile-cache key part)."""
    index: int
    how: str                             # inner | left | semi | anti
    capacity: int                        # pow2 output slots (inner/left)
    n_left: int
    right_rows: int
    #: fixed-width right payloads: (side-input name, output name)
    pays: tuple[tuple[str, str], ...]
    #: string right payloads: (right column name, output name)
    str_pays: tuple[tuple[str, str], ...]
    #: hidden right-row-id column for late string gathering (None if no
    #: string payloads)
    rowid_name: Optional[str]


# probe cache: (left key cols + right table key cols) buffer ids ->
# (rorder, lo, counts, total_inner, total_left)
_SHUFFLE_PROBE_CACHE: dict = {}


def _shuffled_probe(left_keys: list[Column], right, right_on):
    from .stats import _guarded_cache_get, _guarded_cache_put
    right_keys = [right[rn] for rn in right_on]
    buffers = tuple(b for c in (left_keys + right_keys)
                    for b in (c.data, c.offsets, c.validity) if b is not None)
    cache_key = tuple(id(b) for b in buffers)
    hit = _guarded_cache_get(_SHUFFLE_PROBE_CACHE, cache_key, buffers)
    if hit is not None:
        return hit

    from ..ops.join import _factorize_union
    from ..table import Table
    n = left_keys[0].size
    lt = Table([(f"__k{i}__", c) for i, c in enumerate(left_keys)])
    rorder, lo, counts, _rmatched = _factorize_union(
        lt, right, [f"__k{i}__" for i in range(len(left_keys))],
        list(right_on))
    counts32 = counts.astype(jnp.int32)
    totals = jnp.stack([counts.sum(),
                        jnp.maximum(counts, 1).sum()])
    import jax
    t_inner, t_left = (int(x) for x in jax.device_get(totals))  # bind sync
    from ..utils.memory import record_host_sync
    record_host_sync("join.bind_probe", int(totals.nbytes))
    result = (rorder, lo.astype(jnp.int32), counts32, t_inner, t_left)
    _guarded_cache_put(_SHUFFLE_PROBE_CACHE, cache_key, buffers, result)
    return result


def bind_join_shuffled(bound, step, index: int,
                       current_names: list[str]) -> ShuffledJoinMeta:
    """Probe at bind time, register side inputs, produce the static meta."""
    from ..ops.common import pow2_bucket
    right = step.table
    left_keys = []
    for ln, rn in zip(step.left_on, step.right_on):
        if ln in bound.string_cols or ln in bound.dictionaries:
            raise TypeError(
                f"shuffled join probe key {ln!r} is a string column; "
                f"dictionary-encode both sides or use the eager ops.join")
        if rn not in right:
            raise KeyError(f"right-side key {rn!r} not in "
                           f"{list(right.names)}")
        src = bound.shuffle_key_source(ln)
        if src is None:
            raise TypeError(
                f"shuffled join key {ln!r} must be an unmodified input "
                f"column (the bind-time probe reads the input table); "
                f"join first, derive columns after")
        if src.dtype != right[rn].dtype:
            raise TypeError(
                f"join key dtype mismatch: {ln}={src.dtype!r} vs "
                f"{rn}={right[rn].dtype!r} (cast first)")
        left_keys.append(src)

    rorder, lo, counts, t_inner, t_left = _shuffled_probe(
        left_keys, right, step.right_on)
    total = t_left if step.how == "left" else t_inner
    if total >= 1 << 31:
        raise ValueError(
            f"shuffled join expansion is {total} rows (>= 2^31); add a "
            f"pre-join filter or fall back to the eager ops.join in batches")
    capacity = pow2_bucket(total) if step.how in ("inner", "left") else 0

    prefix = f"__sjoin{index}__"
    bound.side_inputs[prefix + "counts"] = Column(data=counts, dtype=INT32)
    pays: list[tuple[str, str]] = []
    str_pays: list[tuple[str, str]] = []
    rowid_name = None
    if step.how in ("inner", "left"):
        bound.side_inputs[prefix + "lo"] = Column(data=lo, dtype=INT32)
        bound.side_inputs[prefix + "rorder"] = Column(data=rorder,
                                                      dtype=INT32)
        right_key_names = set(step.right_on)
        for name, c in right.items():
            if name in right_key_names:
                continue
            if name in current_names:
                raise ValueError(
                    f"join output column {name!r} collides with an "
                    f"existing column; rename one side first")
            if c.dtype is not None and c.dtype.is_nested:
                raise TypeError(
                    f"nested right-side payload {name!r} "
                    f"({c.dtype.type_id.name}) is not supported in compiled "
                    f"plans; drop it from the right table or use the eager "
                    f"ops.join")
            if c.offsets is None:
                side_name = prefix + "pay__" + name
                bound.side_inputs[side_name] = c
                pays.append((side_name, name))
            else:
                str_pays.append((name, name))
        if str_pays:
            rowid_name = prefix + "rowid"
            bound.join_string_srcs[rowid_name] = [
                (right[src], out) for src, out in str_pays]

    return ShuffledJoinMeta(index, step.how, capacity,
                            left_keys[0].size, right.num_rows,
                            tuple(pays), tuple(str_pays), rowid_name)


def trace_join_shuffled(cols, sel, side, meta: ShuffledJoinMeta):
    """Traced expansion (runs inside the plan program).

    Replaces the whole row state: every live column is gathered at its
    owning left row; the output length becomes ``meta.capacity`` with a
    fresh selection marking live slots.  Same slot-ownership trick as
    ops.join._expand_kernel.
    """
    prefix = f"__sjoin{meta.index}__"
    counts = side[prefix + "counts"].data            # (n,) int32

    if meta.how in ("semi", "anti"):
        found = counts > 0
        keep = found if meta.how == "semi" else ~found
        return cols, keep if sel is None else (sel & keep)

    lo = side[prefix + "lo"].data
    rorder = side[prefix + "rorder"].data
    n = meta.n_left
    C = meta.capacity
    live = jnp.ones(n, jnp.bool_) if sel is None else sel
    if meta.how == "left":
        out_counts = jnp.where(live, jnp.maximum(counts, 1), 0)
    else:
        out_counts = jnp.where(live, counts, 0)

    bounds = jnp.cumsum(out_counts)                  # int32: total < 2^31
    total = bounds[-1] if n else jnp.int32(0)
    starts = bounds - out_counts
    pos = jnp.arange(C, dtype=jnp.int32)
    # Scatter every row's start (zero-output rows stack on the next
    # start); prefix count - 1 yields the LAST row starting at or before
    # each slot — the owning row (ops.join._expand_kernel's trick).
    indicator = jnp.zeros(C, jnp.int32).at[
        jnp.clip(starts, 0, C - 1)].add(
            jnp.where(starts < C, 1, 0).astype(jnp.int32))
    lrow = jnp.clip(jnp.cumsum(indicator) - 1, 0, max(n - 1, 0))
    k = pos - jnp.take(starts, lrow)
    matched = jnp.take(counts, lrow) > 0
    rpos = jnp.take(lo, lrow) + k
    empty_right = meta.right_rows == 0    # no matches; left join null-pads
    if empty_right:
        rrow = jnp.zeros(C, jnp.int32)
    else:
        rrow = jnp.take(rorder, jnp.clip(rpos, 0, meta.right_rows - 1))
    out_sel = pos < total

    new: dict[str, Column] = {}
    for name, c in cols.items():
        data = jnp.take(c.data, lrow, axis=0)
        validity = None if c.validity is None else jnp.take(c.validity, lrow)
        new[name] = Column(data=data, validity=validity, dtype=c.dtype)
    for side_name, out_name in meta.pays:
        pay = side[side_name]
        if empty_right:
            data = jnp.zeros((C,) + pay.data.shape[1:], pay.data.dtype)
            validity = jnp.zeros(C, jnp.bool_)
        else:
            data = jnp.take(pay.data, rrow, axis=0)
            validity = (None if pay.validity is None
                        else jnp.take(pay.validity, rrow))
            if meta.how == "left":
                # Unmatched left rows contribute one all-null right slot.
                validity = (matched if validity is None
                            else (validity & matched))
        new[out_name] = Column(data=data, validity=validity, dtype=pay.dtype)
    if meta.rowid_name is not None:
        new[meta.rowid_name] = Column(
            data=rrow, validity=matched if meta.how == "left" else None,
            dtype=INT32)
    return new, out_sel
