"""Distributed execution of compiled plans over a device mesh.

The TPU answer to how spark-rapids runs a physical plan across executors:
instead of shuffling rows between workers over UCX, a distributed plan
runs the SAME per-shard program on every device under ``shard_map`` and
merges only the (cells,)-sized dense group-by accumulators with mesh
collectives — every merge (min/max included, via the psum-gather trick
in compile.py) is expressed as a SUM all-reduce because that is the one
collective the target TPU stack lowers — for the aggregation queries
that dominate TPC-DS, cross-device traffic is a few kilobytes riding ICI
regardless of row count, and there is no shuffle at all.

Plan-shape contract (validated at trace time):

* filter / project / broadcast join run per-shard (the build side is
  replicated to every device, exactly like a Spark broadcast);
* the first group-by must take the dense-domain path; its accumulator
  merge is the only collective.  After it, state is replicated and any
  further steps (sort, limit, more group-bys, filters on aggregates)
  run identically everywhere;
* a global sort or limit of still-sharded rows, or a sorted-fallback
  group-by of sharded rows, raises — that work needs a shuffle and
  belongs to :mod:`..parallel.dist_ops`.

Returns a materialized :class:`..table.Table` when the plan ends
replicated (aggregation plans), or a padded :class:`..parallel.mesh.
DistTable` when it ends row-sharded (pure filter/project pipelines).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..column import Column
from ..dtypes import BOOL8
from ..parallel.mesh import DistTable
from ..table import Table
from .compile import _Bound, _assemble, _final_order, materialize
from .plan import GroupAggStep, Plan

_DIST_COMPILED: dict = {}

# live-count cache per row-mask buffer identity: the empty-input guard
# needs one host sync, but steady-state repeat runs over the same
# DistTable must stay sync-free.
_LIVE_COUNT: dict = {}


def _live_count_cached(row_mask) -> int:
    from .stats import _guarded_cache_get, _guarded_cache_put
    key = (id(row_mask),)
    hit = _guarded_cache_get(_LIVE_COUNT, key, (row_mask,))
    if hit is not None:
        return hit
    count = int(jnp.sum(row_mask))
    _guarded_cache_put(_LIVE_COUNT, key, (row_mask,), count)
    return count


def _ends_replicated(bound: _Bound) -> bool:
    return any(isinstance(s, GroupAggStep) for s in bound.steps)


def run_plan_dist(plan: Plan, dist: DistTable, mesh: Mesh):
    """Execute ``plan`` against a row-sharded table on ``mesh``."""
    axis = mesh.axis_names[0]
    axis_size = int(mesh.shape[axis])
    if _live_count_cached(dist.row_mask) == 0:
        # Degenerate shapes break trace-time assumptions (and the probe
        # under an all-False mask); mirror run_plan's eager fallback.
        from ..parallel.mesh import collect
        from .compile import run_plan_eager
        return run_plan_eager(plan, collect(dist))
    table = dist.table
    bound = _Bound(plan, table, probe_mask=dist.row_mask)
    if bound.string_cols or bound.dictionaries:
        raise TypeError(
            "distributed plans operate on fixed-width columns only "
            "(dictionary-encode strings before sharding, as shard_table "
            "requires)")
    replicated_out = _ends_replicated(bound)

    # The compiled function closes over the concrete mesh via shard_map,
    # so the cache key must identify the mesh by its actual devices, not
    # just its shape.
    mesh_key = (axis, tuple(d.id for d in mesh.devices.flat))
    key = bound.signature() + (mesh_key, replicated_out)
    fn = _DIST_COMPILED.get(key)
    if fn is None:
        program = _assemble(bound.assembly_steps(), tuple(bound.group_metas),
                            tuple(bound.join_metas), axis=axis,
                            axis_size=axis_size)

        def sharded_program(cols, row_mask, side):
            # Padding slots enter as dead rows via the initial selection.
            return program(cols, side, init_sel=row_mask)

        out_spec = PartitionSpec() if replicated_out else PartitionSpec(axis)
        fn = jax.jit(partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(PartitionSpec(axis), PartitionSpec(axis),
                      PartitionSpec()),
            out_specs=(out_spec, out_spec),
            check_vma=False,
        )(sharded_program))
        _DIST_COMPILED[key] = fn

    out_cols, sel = fn(bound.exec_cols, dist.row_mask, bound.side_inputs)
    if replicated_out:
        return materialize(bound, out_cols, sel)
    order = [nm for nm in _final_order(plan.steps, bound.input_names)
             if nm in out_cols]
    order += [nm for nm in out_cols if nm not in order]
    return DistTable(table=Table([(nm, out_cols[nm]) for nm in order]),
                     row_mask=sel.astype(jnp.bool_))
