"""Live-query registry — in-flight heartbeat state for running queries.

Every observability layer before this one (QueryMetrics, the span
timeline, the history sink, the cost ledger) is post-hoc: a long
dist_stream sweep gives zero signal until it finishes.  This module is
the live side — each execution path (``run_plan``, ``analyze_plan``,
``run_plan_stream``, ``run_plan_dist``, ``run_plan_dist_stream``)
registers a :class:`LiveQuery` at start and publishes heartbeat state as
it executes: phase, batches completed / in-flight per shard, live rows,
ICI bytes, donation hits, recovery rungs taken, HBM occupancy, and
elapsed + rows/sec.  The serving layer's admission control (ROADMAP open
item 2) and the ``/queries`` endpoint of obs/server.py both read the
same snapshots.

Contract (mirrors obs/metrics.py):

* **off (default)** — with ``SRT_METRICS`` unset, :func:`start` hands
  back the ONE shared :data:`NULL_LIVE` record whose methods do nothing;
  executors pay one env read per *query*, never per batch or row.  An
  explicit progress observer (``Plan.run(progress=...)``,
  ``run_plan_stream(on_progress=...)``) opts a single query in without
  the env flag.
* **on** — scalar updates are plain attribute writes on the record
  (GIL-atomic increments, no lock on the hot path); the registry lock is
  taken only at query start/finish and by snapshot readers, and a small
  per-record lock guards only the container state (per-shard progress
  dict, rung deque) so concurrent publishers never race a ``/queries``
  or ``/metrics`` scrape mid-iteration.  Readers may still observe
  scalar heartbeats mid-update — snapshots are monitoring data, not a
  ledger.
* jax-free at module load (tests/test_import_hygiene.py), like the rest
  of ``obs``.

The publishing helpers (:func:`phase`, :func:`rung`, :func:`add_ici`,
:func:`note_hbm`) act on the *current* query of the calling thread — a
thread-local stack maintained by :func:`start`/:meth:`LiveQuery.finish`
— so deep layers (the recovery ladder, the mesh ICI accountant, the HBM
sampler) publish without any record plumbing.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from ..config import live_recent_keep, metrics_enabled

#: Recovery rungs kept per live record (newest last).
RUNG_KEEP = 16

_LOCK = threading.Lock()
_ACTIVE: "OrderedDict[int, LiveQuery]" = OrderedDict()
# Unbounded deque, LRU-trimmed to SRT_LIVE_RECENT on every finish:
# sustained serving retires queries forever, and the retention cap is
# what keeps the postmortem-lookup window from growing memory.
_RECENT: deque = deque()
_TLS = threading.local()


class _NullLiveQuery:
    """Shared do-nothing record handed out while ``SRT_METRICS`` is
    unset (and no observer asked for progress).  Duck-types
    :class:`LiveQuery`; all mutators discard, :meth:`snapshot` is ``{}``."""

    __slots__ = ()

    query_id = 0
    fingerprint = ""

    def set_phase(self, name: str) -> None:
        pass

    def batch_in(self, rows: int = 0) -> None:
        pass

    def batch_out(self, rows: int = 0) -> None:
        pass

    def set_inflight(self, depth: int) -> None:
        pass

    def set_shards(self, n: int) -> None:
        pass

    def shard_batches_done(self, shards: int = 1) -> None:
        pass

    def donation(self, hit: bool) -> None:
        pass

    def add_ici(self, nbytes: int) -> None:
        pass

    def set_live_rows(self, rows: int) -> None:
        pass

    def set_rows(self, rows_in: Optional[int] = None,
                 rows_out: Optional[int] = None) -> None:
        pass

    def set_total_batches(self, n: int) -> None:
        pass

    def rung(self, step: str, site: str = "") -> None:
        pass

    def note_hbm(self, peak_bytes: int) -> None:
        pass

    def finish(self, status: str = "done", error: Optional[str] = None,
               output_rows: Optional[int] = None) -> None:
        pass

    def snapshot(self) -> dict:
        return {}


#: THE null record — identity-comparable so tests can assert the no-op
#: contract (``live.start(...) is NULL_LIVE`` when metrics are off).
NULL_LIVE = _NullLiveQuery()


class LiveQuery:
    """One in-flight query's heartbeat state.

    Mutators are single attribute writes / increments — no lock (the GIL
    makes ``int`` increments atomic enough for monitoring; the registry
    lock only guards start/finish membership).  ``snapshot()`` renders a
    JSON-safe dict and is what the server and the ``top`` view consume.
    """

    __slots__ = ("query_id", "mode", "fingerprint", "phase", "status",
                 "error", "started_unix", "_t0", "_t_end", "input_rows",
                 "rows_in", "rows_out", "live_rows", "batches_in",
                 "batches_done", "total_batches", "inflight",
                 "peak_inflight", "shards", "shard_done", "ici_bytes",
                 "donation_hits", "donation_misses", "rungs",
                 "hbm_peak_bytes", "_observer", "_lock")

    def __init__(self, query_id: int, mode: str, fingerprint: str = "",
                 input_rows: int = 0, shards: int = 0,
                 observer: Optional[Callable[[dict], None]] = None):
        self.query_id = query_id
        self.mode = mode
        self.fingerprint = fingerprint
        self.phase = "start"
        self.status = "running"
        self.error: Optional[str] = None
        self.started_unix = time.time()
        self._t0 = time.perf_counter()
        self._t_end: Optional[float] = None
        self.input_rows = input_rows
        self.rows_in = 0
        self.rows_out = 0
        self.live_rows = 0
        self.batches_in = 0
        self.batches_done = 0
        self.total_batches = 0
        self.inflight = 0
        self.peak_inflight = 0
        self.shards = shards
        self.shard_done: Dict[int, int] = {}
        self.ici_bytes = 0
        self.donation_hits = 0
        self.donation_misses = 0
        self.rungs: deque = deque(maxlen=RUNG_KEEP)
        self.hbm_peak_bytes = 0
        self._observer = observer
        # Guards the CONTAINER state (shard_done dict, rungs deque)
        # against a /queries or /metrics scrape iterating mid-mutation
        # — scalar heartbeat writes stay lock-free (GIL-atomic), so the
        # per-batch hot path is unchanged.
        self._lock = threading.Lock()

    # -- publishers (hot path: attribute writes only) --------------------

    def set_phase(self, name: str) -> None:
        self.phase = name
        self._notify()

    def batch_in(self, rows: int = 0) -> None:
        self.batches_in += 1
        self.rows_in += rows

    def batch_out(self, rows: int = 0) -> None:
        self.batches_done += 1
        self.rows_out += rows
        self._notify()

    def set_inflight(self, depth: int) -> None:
        self.inflight = depth
        if depth > self.peak_inflight:
            self.peak_inflight = depth

    def set_shards(self, n: int) -> None:
        self.shards = n
        with self._lock:
            for s in range(n):
                self.shard_done.setdefault(s, 0)

    def shard_batches_done(self, shards: int = 1) -> None:
        """One batch finished on each of the first ``shards`` shards
        (SPMD dispatch runs every batch on every shard)."""
        with self._lock:
            done = self.shard_done
            for s in range(shards):
                done[s] = done.get(s, 0) + 1

    def donation(self, hit: bool) -> None:
        if hit:
            self.donation_hits += 1
        else:
            self.donation_misses += 1

    def add_ici(self, nbytes: int) -> None:
        self.ici_bytes += int(nbytes)

    def set_live_rows(self, rows: int) -> None:
        self.live_rows = int(rows)

    def set_rows(self, rows_in: Optional[int] = None,
                 rows_out: Optional[int] = None) -> None:
        if rows_in is not None:
            self.rows_in = int(rows_in)
        if rows_out is not None:
            self.rows_out = int(rows_out)

    def set_total_batches(self, n: int) -> None:
        """Expected batch count when the caller knows it (benchmarks and
        bounded feeds) — enables the ETA in :meth:`snapshot`."""
        self.total_batches = int(n)

    def rung(self, step: str, site: str = "") -> None:
        with self._lock:
            self.rungs.append(f"{site}:{step}" if site else step)
        self._notify()

    def note_hbm(self, peak_bytes: int) -> None:
        if peak_bytes > self.hbm_peak_bytes:
            self.hbm_peak_bytes = int(peak_bytes)

    # -- lifecycle -------------------------------------------------------

    def finish(self, status: str = "done", error: Optional[str] = None,
               output_rows: Optional[int] = None) -> None:
        if self.status != "running":
            return
        self._t_end = time.perf_counter()
        self.status = status
        self.error = error
        if output_rows is not None:
            self.rows_out = int(output_rows)
        self.phase = status
        keep = live_recent_keep()
        evicted = 0
        with _LOCK:
            _ACTIVE.pop(self.query_id, None)
            _RECENT.append(self)
            while len(_RECENT) > keep:
                _RECENT.popleft()
                evicted += 1
        if evicted:
            # LRU drops were previously invisible; the counter exports
            # as srt_live_recent_evictions_total on /metrics.
            from .metrics import counter
            counter("live.recent_evictions").inc(evicted)
        stack = getattr(_TLS, "stack", None)
        if stack and self in stack:
            stack.remove(self)
        self._notify()

    # -- reading ---------------------------------------------------------

    def elapsed(self) -> float:
        end = self._t_end if self._t_end is not None else time.perf_counter()
        return max(end - self._t0, 0.0)

    def snapshot(self) -> dict:
        elapsed = self.elapsed()
        rows = self.rows_in or self.input_rows
        rows_per_sec = rows / elapsed if elapsed > 0 and rows else 0.0
        eta = None
        if (self.status == "running" and self.total_batches
                and self.batches_done):
            remaining = max(self.total_batches - self.batches_done, 0)
            eta = round(remaining * (elapsed / self.batches_done), 3)
        with self._lock:
            rungs = list(self.rungs)
            shard_done = dict(self.shard_done)
        return {
            "query_id": self.query_id,
            "fingerprint": self.fingerprint,
            "mode": self.mode,
            "phase": self.phase,
            "status": self.status,
            "error": self.error,
            "started_unix": round(self.started_unix, 3),
            "elapsed_seconds": round(elapsed, 6),
            "input_rows": self.input_rows,
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "live_rows": self.live_rows,
            "rows_per_sec": round(rows_per_sec, 1),
            "eta_seconds": eta,
            "batches_in": self.batches_in,
            "batches_done": self.batches_done,
            "total_batches": self.total_batches,
            "inflight": self.inflight,
            "peak_inflight": self.peak_inflight,
            "shards": self.shards,
            "shard_batches": {str(s): n
                              for s, n in sorted(shard_done.items())},
            "ici_bytes": self.ici_bytes,
            "donation_hits": self.donation_hits,
            "donation_misses": self.donation_misses,
            "recovery": {"rungs": rungs,
                         "last_rung": rungs[-1] if rungs else "",
                         "count": len(rungs)},
            "hbm_peak_bytes": self.hbm_peak_bytes,
        }

    def _notify(self) -> None:
        if self._observer is None:
            return
        try:
            self._observer(self.snapshot())
        except Exception:        # an observer must never kill the query
            pass


def as_observer(progress: Any) -> Optional[Callable[[dict], None]]:
    """Normalize a ``progress=`` argument: callables pass through,
    truthy non-callables mean the stderr one-liner, falsy means None."""
    if progress is None or progress is False:
        return None
    return progress if callable(progress) else print_progress


def start(mode: str, plan: Any = None, query_id: Optional[int] = None,
          input_rows: int = 0, shards: int = 0,
          observer: Optional[Callable[[dict], None]] = None,
          force: bool = False,
          fingerprint: Optional[str] = None) -> Any:
    """Register a query; returns its :class:`LiveQuery` (or
    :data:`NULL_LIVE` when telemetry is off and nobody is observing).

    The ONE gate of the zero-cost-off contract: everything downstream is
    method calls on the returned record.  ``force`` (or a non-None
    ``observer``) opts this query in regardless of ``SRT_METRICS`` —
    the explicit-progress surfaces use it.  Pass ``fingerprint`` when the
    caller already hashed the plan (QueryMetrics producers do) so the
    plan is not hashed twice.
    """
    if not (metrics_enabled() or force or observer is not None):
        return NULL_LIVE
    if query_id is None:
        from .query import next_query_id
        query_id = next_query_id()
    if fingerprint is None:
        fingerprint = ""
        if plan is not None:
            from .history import plan_fingerprint
            fingerprint = plan_fingerprint(plan)
    lq = LiveQuery(query_id, mode, fingerprint=fingerprint,
                   input_rows=input_rows, shards=shards, observer=observer)
    with _LOCK:
        _ACTIVE[query_id] = lq
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(lq)
    from ..config import live_server_enabled
    if live_server_enabled():
        from . import server
        server.maybe_start()
    lq._notify()
    return lq


def current() -> Optional[LiveQuery]:
    """The calling thread's innermost in-flight query, or None."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


# -- ambient publishers: deep layers (recovery ladder, ICI accountant,
# HBM sampler) call these without holding a record ----------------------

def phase(name: str) -> None:
    lq = current()
    if lq is not None:
        lq.set_phase(name)


def rung(step: str, site: str = "") -> None:
    lq = current()
    if lq is not None:
        lq.rung(step, site)


def add_ici(nbytes: int) -> None:
    lq = current()
    if lq is not None:
        lq.add_ici(nbytes)


def note_hbm(peak_bytes: int) -> None:
    lq = current()
    if lq is not None:
        lq.note_hbm(peak_bytes)


# -- serving integration -------------------------------------------------

#: Optional callable returning a JSON-safe list of queued-query dicts
#: (serve/scheduler.py registers one); pulled into every
#: :func:`snapshot_all` so /queries, /metrics and ``obs top`` see the
#: admission queue without the obs layer importing serve.
_QUEUED_PROVIDER: Optional[Callable[[], List[dict]]] = None


def set_queued_provider(
        fn: Optional[Callable[[], List[dict]]]) -> None:
    """Register (or clear, with None) the queued-queries provider."""
    global _QUEUED_PROVIDER
    _QUEUED_PROVIDER = fn


# -- registry reads ------------------------------------------------------

def get(query_id: int) -> Optional[dict]:
    """Snapshot of one query (in-flight or recent), or None."""
    with _LOCK:
        lq = _ACTIVE.get(query_id)
        if lq is None:
            for r in _RECENT:
                if r.query_id == query_id:
                    lq = r
                    break
    return lq.snapshot() if lq is not None else None


def snapshot_all() -> dict:
    """The ``/queries`` payload: in-flight and recently finished queries,
    newest last, plus the publishing process's identity."""
    with _LOCK:
        active = list(_ACTIVE.values())
        recent = list(_RECENT)
    provider = _QUEUED_PROVIDER
    queued: List[dict] = []
    if provider is not None:
        try:
            queued = list(provider())
        except Exception:       # a scrape must never fail on serve state
            queued = []
    return {
        "pid": os.getpid(),
        "unix_time": round(time.time(), 3),
        "in_flight": [lq.snapshot() for lq in active],
        "queued": queued,
        "recent": [lq.snapshot() for lq in recent],
    }


def reset() -> None:
    """Drop all live/recent records (test isolation)."""
    with _LOCK:
        _ACTIVE.clear()
        _RECENT.clear()
    _TLS.stack = []


def print_progress(snap: dict) -> None:
    """The ``progress=True`` observer: one overwriting stderr line per
    heartbeat."""
    if not snap:
        return
    sys.stderr.write(
        "\r[q{qid} {mode}] {phase:<12} {done}/{total} batches "
        "{rows:,} rows {rps:,.0f} rows/s {elapsed:.1f}s {rung}".format(
            qid=snap["query_id"], mode=snap["mode"], phase=snap["phase"],
            done=snap["batches_done"],
            total=snap["total_batches"] or "?",
            rows=snap["rows_in"] or snap["input_rows"],
            rps=snap["rows_per_sec"], elapsed=snap["elapsed_seconds"],
            rung=snap["recovery"]["last_rung"]))
    if snap["status"] != "running":
        sys.stderr.write("\n")
    sys.stderr.flush()


__all__: List[str] = [
    "LiveQuery", "NULL_LIVE", "RUNG_KEEP", "add_ici",
    "as_observer", "current", "get", "note_hbm", "phase",
    "print_progress", "reset", "rung", "set_queued_provider",
    "snapshot_all", "start",
]
