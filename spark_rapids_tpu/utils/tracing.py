"""Named profiler scopes — the NVTX-ranges analog.

The reference's tracing story is NVTX ranges in the cudf Java layer behind
``-Dai.rapids.cudf.nvtx.enabled`` (pom.xml:84, :366-369) plus ``-lineinfo``
device compiles for profiler introspection (ConfigureCUDA.cmake:33-37).  The
TPU equivalents are ``jax.profiler`` trace annotations (visible in
TensorBoard/XPlane captures and Perfetto) and jitted-function naming.

Everything here is a no-op unless ``SRT_TRACE=1`` (config.trace_enabled), so
instrumented code pays nothing in production — the same opt-in contract as
the NVTX toggle.

Usage::

    with trace("convert_to_rows"):
        ...
    @traced
    def shuffle(...): ...

``start_server(port)`` re-exports the on-demand profiler server so a running
job can be attached to (the TPU replacement for attaching nsys to a live
process).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Iterator, TypeVar

from ..config import trace_enabled

_F = TypeVar("_F", bound=Callable)


@contextlib.contextmanager
def trace(name: str) -> Iterator[None]:
    """Named scope visible in jax profiler captures (NVTX push/pop analog)."""
    if not trace_enabled():
        yield
        return
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


def traced(fn: _F) -> _F:
    """Decorator form of :func:`trace`, scope named after the function."""
    name = f"{fn.__module__.rsplit('.', 1)[-1]}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with trace(name):
            return fn(*args, **kwargs)

    return wrapper  # type: ignore[return-value]


def start_server(port: int = 9012):
    """Start the on-demand jax profiler server (attach via TensorBoard)."""
    import jax.profiler
    return jax.profiler.start_server(port)
