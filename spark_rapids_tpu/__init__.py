"""spark_rapids_tpu — a TPU-native columnar data-processing framework.

Brand-new implementation of the capability envelope of the reference
``spark-rapids-jni`` (GPU columnar JNI library for Apache Spark; see SURVEY.md):
device-resident columnar tables, byte-exact Spark fixed-width row ↔ columnar
conversion, the cuDF-class op set (cast, sort, group-by, join, strings/regex,
Parquet), and distributed shuffle — designed for TPU (JAX/XLA/Pallas, device
meshes, XLA collectives) rather than translated from CUDA.

Layer map (TPU counterpart of SURVEY.md §1):

  host app (Spark executor / Python driver)
    → :mod:`spark_rapids_tpu` Python API + native C ABI bridge (:mod:`.ffi`)
      → eager ops layer (:mod:`.ops`) — jit-cached XLA programs per schema
        → column/table model (:mod:`.column`, :mod:`.table`) — pytrees of
          HBM-resident arrays
          → XLA/Pallas kernels (:mod:`.rows.pallas_kernels`, op kernels)
            → TPU (MXU/VPU/VMEM, ICI collectives via :mod:`.parallel`)
"""

import jax as _jax

# 64-bit dtypes (Spark longs/doubles/decimal64) are part of the data model.
# Must be set before any array is created.
_jax.config.update("jax_enable_x64", True)


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache (config.compile_cache_dir): per-schema
    query programs cost minutes to compile on TPU and sub-second on a
    cross-process cache hit."""
    from .config import compile_cache_dir
    path = compile_cache_dir()
    if path is None or _jax.config.jax_compilation_cache_dir:
        return                        # disabled, or the user already chose
    # Cache accelerator platforms only: CPU compiles are cheap, and
    # XLA:CPU AOT artifacts bake in exact host machine features —
    # reloading them on a slightly different host (shared ~/.cache,
    # container images) warns about and risks SIGILL.
    platforms = _jax.config.jax_platforms or ""
    if platforms:
        # Explicit priority list: the first entry wins backend selection.
        if platforms.split(",")[0].strip() == "cpu":
            return
    else:
        # Unset: resolve the backend (the common TPU-host default).  This
        # initializes the runtime, which package users pay on first array
        # creation anyway.
        try:
            if _jax.default_backend() == "cpu":
                return
        except Exception:
            return
    try:
        import os as _os
        _os.makedirs(path, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", path)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except OSError:
        pass                          # unwritable cache home: run uncached


_enable_compile_cache()

from . import dtypes  # noqa: E402
from . import exec  # noqa: E402  (whole-plan compiler)
from .column import Column  # noqa: E402
from .table import Table, assert_tables_equal  # noqa: E402
from .dtypes import DType, TypeId  # noqa: E402

__version__ = "26.02.0a0"

__all__ = [
    "Column",
    "DType",
    "Table",
    "TypeId",
    "assert_tables_equal",
    "dtypes",
    "exec",
    "__version__",
]
