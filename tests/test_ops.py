"""Ops layer tests: cast, binary, filter, sort, groupby, join, reductions.

Oracle strategy mirrors the reference's (round-trip/self-consistency plus
known-answer tables); pandas is used as an independent oracle for the random
sweeps.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import Column, Table, assert_tables_equal
from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu import ops
from spark_rapids_tpu.ops import reductions


class TestCast:
    def test_int_widen_narrow(self):
        c = Column.from_pylist([1, None, 300], dt.INT32)
        assert ops.cast(c, dt.INT64).to_pylist() == [1, None, 300]
        assert ops.cast(c, dt.INT16).to_pylist() == [1, None, 300]
        assert ops.cast(c, dt.INT8).to_pylist() == [1, None, 300 - 256]

    def test_float_to_int_truncates(self):
        c = Column.from_pylist([1.9, -1.9, None], dt.FLOAT64)
        assert ops.cast(c, dt.INT32).to_pylist() == [1, -1, None]

    def test_bool_casts(self):
        c = Column.from_pylist([0, 5, None], dt.INT32)
        assert ops.cast(c, dt.BOOL8).to_pylist() == [False, True, None]
        b = Column.from_pylist([True, False, None], dt.BOOL8)
        assert ops.cast(b, dt.INT64).to_pylist() == [1, 0, None]

    def test_decimal_rescale(self):
        c = Column.from_pylist([12345, -678, None], dt.decimal64(-2))  # 123.45, -6.78
        up = ops.cast(c, dt.decimal64(-4))
        assert up.to_pylist() == [1234500, -67800, None]
        down = ops.cast(c, dt.decimal64(-1))   # truncation toward zero
        assert down.to_pylist() == [1234, -67, None]

    def test_decimal_to_float_and_back(self):
        c = Column.from_pylist([12345], dt.decimal32(-2))
        f = ops.cast(c, dt.FLOAT64)
        assert f.to_pylist() == [123.45]
        back = ops.cast(f, dt.decimal64(-2))
        assert back.to_pylist() == [12345]

    def test_decimal_to_int_truncates(self):
        c = Column.from_pylist([199, -199], dt.decimal32(-2))  # 1.99, -1.99
        assert ops.cast(c, dt.INT32).to_pylist() == [1, -1]


class TestBinary:
    def test_null_propagation(self):
        a = Column.from_pylist([1, None, 3], dt.INT64)
        b = Column.from_pylist([10, 20, None], dt.INT64)
        assert ops.binary_op(a, b, "add").to_pylist() == [11, None, None]

    def test_scalar_broadcast(self):
        a = Column.from_pylist([1, None, 3], dt.INT64)
        assert ops.binary_op(a, 5, "mul").to_pylist() == [5, None, 15]

    def test_comparisons_produce_bool8(self):
        a = Column.from_pylist([1, 2, None], dt.INT32)
        r = ops.binary_op(a, 2, "lt")
        assert r.dtype == dt.BOOL8
        assert r.to_pylist() == [True, False, None]

    def test_int_division_promotes_to_float(self):
        a = Column.from_pylist([7, 8], dt.INT32)
        r = ops.binary_op(a, 2, "truediv")
        assert r.dtype == dt.FLOAT64
        assert r.to_pylist() == [3.5, 4.0]

    def test_decimal_add_same_scale(self):
        a = Column.from_pylist([100], dt.decimal64(-2))
        b = Column.from_pylist([23], dt.decimal64(-2))
        r = ops.binary_op(a, b, "add")
        assert r.dtype == dt.decimal64(-2)
        assert r.to_pylist() == [123]

    def test_decimal_mul_adds_scales(self):
        a = Column.from_pylist([150], dt.decimal64(-2))   # 1.50
        b = Column.from_pylist([200], dt.decimal64(-2))   # 2.00
        r = ops.binary_op(a, b, "mul")
        assert r.dtype == dt.decimal64(-4)
        assert r.to_pylist() == [30000]                   # 3.0000

    def test_if_else_and_fill_null(self):
        cond = Column.from_pylist([True, False, True], dt.BOOL8)
        a = Column.from_pylist([1, 2, None], dt.INT64)
        r = ops.if_else(cond, a, -1)
        assert r.to_pylist()[:2] == [1, -1]
        assert ops.fill_null(a, 0).to_pylist() == [1, 2, 0]

    def test_is_null(self):
        a = Column.from_pylist([1, None], dt.INT64)
        assert ops.is_null(a).to_pylist() == [False, True]


class TestFilter:
    def test_mask_filter(self):
        t = Table.from_pydict({"a": [1, 2, 3, 4], "s": ["w", "x", "y", "z"]})
        out = ops.apply_boolean_mask(t, jnp.array([True, False, True, False]))
        assert out.to_pydict() == {"a": [1, 3], "s": ["w", "y"]}

    def test_null_mask_drops(self):
        t = Table.from_pydict({"a": [1, 2, 3]})
        mask = Column.from_pylist([True, None, True], dt.BOOL8)
        assert ops.apply_boolean_mask(t, mask).to_pydict() == {"a": [1, 3]}

    def test_drop_nulls(self):
        t = Table.from_pydict({"a": [1, None, 3], "b": [None, 2.0, 3.0]})
        assert ops.drop_nulls(t).to_pydict() == {"a": [3], "b": [3.0]}
        assert ops.drop_nulls(t, ["a"]).to_pydict() == {"a": [1, 3], "b": [None, 3.0]}


class TestSort:
    def test_single_key_with_nulls(self):
        t = Table.from_pydict({"k": [3, None, 1, 2]})
        out = ops.sort_by(t, "k")
        assert out.to_pydict() == {"k": [None, 1, 2, 3]}   # nulls first (asc)

    def test_descending_nulls_last(self):
        t = Table.from_pydict({"k": [3, None, 1, 2]})
        out = ops.sort_by(t, "k", ascending=[False])
        assert out.to_pydict() == {"k": [3, 2, 1, None]}

    def test_multi_key_stable(self):
        t = Table.from_pydict({"a": [1, 2, 1, 2, 1], "b": [9, 8, 7, 6, 5],
                               "tag": [0, 1, 2, 3, 4]})
        out = ops.sort_by(t, ["a", "b"])
        assert out.to_pydict()["a"] == [1, 1, 1, 2, 2]
        assert out.to_pydict()["b"] == [5, 7, 9, 6, 8]

    def test_mixed_direction(self):
        t = Table.from_pydict({"a": [1, 2, 1, 2], "b": [5, 6, 7, 8]})
        out = ops.sort_by(t, ["a", "b"], ascending=[True, False])
        assert out.to_pydict()["b"] == [7, 5, 8, 6]

    def test_nan_sorts_last_ascending(self):
        t = Table.from_pydict({"k": [float("nan"), 1.0, 2.0]},
                              dtypes={"k": dt.FLOAT64})
        got = ops.sort_by(t, "k").to_pydict()["k"]
        assert got[:2] == [1.0, 2.0] and np.isnan(got[2])

    def test_float_descending(self):
        t = Table.from_pydict({"k": [1.5, -2.0, 0.5]}, dtypes={"k": dt.FLOAT64})
        assert ops.sort_by(t, "k", ascending=[False]).to_pydict()["k"] == [1.5, 0.5, -2.0]

    def test_random_sweep_vs_pandas(self, rng):
        n = 1000
        a = rng.integers(0, 50, n)
        b = rng.standard_normal(n)
        t = Table.from_pydict({"a": a.astype(np.int64).tolist(),
                               "b": b.tolist()},
                              dtypes={"a": dt.INT64, "b": dt.FLOAT64})
        got = ops.sort_by(t, ["a", "b"]).to_pydict()
        exp = pd.DataFrame({"a": a, "b": b}).sort_values(["a", "b"], kind="stable")
        assert got["a"] == exp["a"].tolist()
        assert got["b"] == exp["b"].tolist()


class TestNullTieBreak:
    def test_secondary_key_orders_null_primary_rows(self):
        # Among rows whose PRIMARY key is null, ordering must fall through
        # to the secondary key — not to the null rows' undefined payloads.
        t = Table.from_pydict(
            {"a": [None, None, None, 1], "b": [3, 1, 2, 0]},
            dtypes={"a": dt.INT64, "b": dt.INT32})
        out = ops.sort_by(t, ["a", "b"]).to_pydict()
        assert out["a"] == [None, None, None, 1]
        assert out["b"] == [1, 2, 3, 0]


class TestGroupBy:
    def test_basic_aggs(self):
        t = Table.from_pydict({"k": [1, 2, 1, 2, 1], "v": [10, 20, 30, None, 50]},
                              dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": ["sum", "count", "min", "max", "mean"]})
        assert out.to_pydict() == {
            "k": [1, 2],
            "v_sum": [90, 20],
            "v_count": [3, 1],
            "v_min": [10, 20],
            "v_max": [50, 20],
            "v_mean": [30.0, 20.0],
        }

    def test_null_key_is_a_group(self):
        t = Table.from_pydict({"k": [1, None, 1, None], "v": [1, 2, 3, 4]},
                              dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": "sum"})
        assert out.to_pydict() == {"k": [None, 1], "v": [6, 4]}

    def test_all_null_group_sum_is_null(self):
        t = Table.from_pydict({"k": [1, 1, 2], "v": [None, None, 5]},
                              dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": ["sum", "count", "min"]})
        assert out.to_pydict()["v_sum"] == [None, 5]
        assert out.to_pydict()["v_count"] == [0, 1]
        assert out.to_pydict()["v_min"] == [None, 5]

    def test_first_last(self):
        t = Table.from_pydict({"k": [1, 1, 2], "v": [10, 20, 30]},
                              dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": ["first", "last"]})
        assert out.to_pydict()["v_first"] == [10, 30]
        assert out.to_pydict()["v_last"] == [20, 30]

    def test_nunique(self):
        t = Table.from_pydict(
            {"k": [1, 1, 1, 2, 2, None, None],
             "v": [10, 10, 20, 30, None, 10, None]},
            dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": ["nunique", "count"]})
        # null key rows form their own group; null VALUES are excluded
        # from the distinct count (cuDF nunique default).
        assert out.to_pydict() == {
            "k": [None, 1, 2],
            "v_nunique": [1, 2, 1],
            "v_count": [1, 3, 1],
        }

    def test_nunique_random_vs_numpy(self, rng=None):
        import numpy as np
        rng = np.random.default_rng(11)
        n = 5000
        k = rng.integers(0, 40, n)
        v = rng.integers(0, 25, n)
        vmask = rng.random(n) > 0.2
        t = Table([
            ("k", Column.from_numpy(k.astype(np.int64))),
            ("v", Column.from_numpy(v.astype(np.int64), validity=vmask)),
        ])
        out = ops.groupby_agg(t, ["k"], [("v", "nunique", "nv")]).to_pydict()
        for key, got in zip(out["k"], out["nv"]):
            want = len(set(v[(k == key) & vmask]))
            assert got == want, (key, got, want)

    def test_median_random_vs_numpy(self):
        import numpy as np
        rng = np.random.default_rng(13)
        n = 4000
        k = rng.integers(0, 30, n)
        v = rng.normal(size=n)
        vmask = rng.random(n) > 0.25
        t = Table([
            ("k", Column.from_numpy(k.astype(np.int64))),
            ("v", Column.from_numpy(v, validity=vmask)),
        ])
        out = ops.groupby_agg(t, ["k"], [("v", "median", "m")]).to_pydict()
        for key, got in zip(out["k"], out["m"]):
            vals = v[(k == key) & vmask]
            want = float(np.median(vals)) if vals.size else None
            if want is None:
                assert got is None
            else:
                assert got == pytest.approx(want, rel=1e-12), key

    def test_median_all_null_group(self):
        t = Table.from_pydict({"k": [1, 1, 2], "v": [None, None, 7]},
                              dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.groupby_agg(t, ["k"], [("v", "median", "m")]).to_pydict()
        assert out["m"] == [None, 7.0]

    def test_nunique_strings(self):
        t = Table.from_pydict(
            {"k": [1, 1, 1, 2], "s": ["a", "b", "a", None]},
            dtypes={"k": dt.INT32, "s": dt.STRING})
        out = ops.groupby_agg(t, ["k"], [("s", "nunique", "ns")])
        assert out.to_pydict()["ns"] == [2, 0]

    def test_multi_key(self):
        t = Table.from_pydict({"a": [1, 1, 2, 2], "b": [1, 2, 1, 1],
                               "v": [1.0, 2.0, 3.0, 4.0]},
                              dtypes={"a": dt.INT32, "b": dt.INT32, "v": dt.FLOAT64})
        out = ops.groupby(t, ["a", "b"]).agg({"v": "sum"})
        assert out.to_pydict() == {"a": [1, 1, 2], "b": [1, 2, 1],
                                   "v": [1.0, 2.0, 7.0]}

    def test_var_std(self):
        t = Table.from_pydict({"k": [1, 1, 1], "v": [1.0, 2.0, 3.0]},
                              dtypes={"k": dt.INT32, "v": dt.FLOAT64})
        out = ops.groupby(t, "k").agg({"v": ["var", "std"]})
        assert out.to_pydict()["v_var"] == [1.0]
        assert out.to_pydict()["v_std"] == [1.0]

    def test_empty_table(self):
        t = Table({"k": Column.from_numpy(np.zeros(0, np.int32)),
                   "v": Column.from_numpy(np.zeros(0, np.int64))})
        out = ops.groupby(t, "k").agg({"v": "sum"})
        assert out.num_rows == 0

    def test_random_sweep_vs_pandas(self, rng):
        n = 2000
        k = rng.integers(0, 37, n).astype(np.int64)
        v = rng.standard_normal(n)
        t = Table.from_pydict({"k": k.tolist(), "v": v.tolist()},
                              dtypes={"k": dt.INT64, "v": dt.FLOAT64})
        out = ops.groupby(t, "k").agg({"v": ["sum", "count", "min", "max"]})
        exp = (pd.DataFrame({"k": k, "v": v}).groupby("k")["v"]
               .agg(["sum", "count", "min", "max"]).reset_index())
        got = out.to_pydict()
        assert got["k"] == exp["k"].tolist()
        np.testing.assert_allclose(got["v_sum"], exp["sum"].to_numpy(), rtol=1e-12)
        assert got["v_count"] == exp["count"].tolist()
        np.testing.assert_allclose(got["v_min"], exp["min"].to_numpy())
        np.testing.assert_allclose(got["v_max"], exp["max"].to_numpy())


class TestJoin:
    def test_inner_basic(self):
        left = Table.from_pydict({"k": [1, 2, 3], "l": [10, 20, 30]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [2, 3, 4], "r": [200, 300, 400]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k")
        assert out.to_pydict() == {"k": [2, 3], "l": [20, 30], "r": [200, 300]}

    def test_inner_one_to_many(self):
        left = Table.from_pydict({"k": [1, 2], "l": [10, 20]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [2, 2, 2], "r": [1, 2, 3]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k")
        assert out.to_pydict() == {"k": [2, 2, 2], "l": [20, 20, 20], "r": [1, 2, 3]}

    def test_left_join_unmatched_null(self):
        left = Table.from_pydict({"k": [1, 2], "l": [10, 20]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [2], "r": [200]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k", how="left")
        assert out.to_pydict() == {"k": [1, 2], "l": [10, 20], "r": [None, 200]}

    def test_null_keys_never_match(self):
        left = Table.from_pydict({"k": [1, None], "l": [10, 20]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [None, 1], "r": [100, 200]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        inner = ops.join(left, right, on="k")
        assert inner.to_pydict() == {"k": [1], "l": [10], "r": [200]}
        leftj = ops.join(left, right, on="k", how="left")
        assert leftj.to_pydict() == {"k": [1, None], "l": [10, 20], "r": [200, None]}

    def test_semi_anti(self):
        left = Table.from_pydict({"k": [1, 2, 3]}, dtypes={"k": dt.INT32})
        right = Table.from_pydict({"k": [2, 2]}, dtypes={"k": dt.INT32})
        assert ops.join(left, right, on="k", how="semi").to_pydict() == {"k": [2]}
        assert ops.join(left, right, on="k", how="anti").to_pydict() == {"k": [1, 3]}

    def test_multi_key_join(self):
        left = Table.from_pydict({"a": [1, 1, 2], "b": [1, 2, 1], "l": [10, 20, 30]},
                                 dtypes={"a": dt.INT32, "b": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"a": [1, 2], "b": [2, 1], "r": [100, 200]},
                                  dtypes={"a": dt.INT32, "b": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on=["a", "b"])
        assert out.to_pydict() == {"a": [1, 2], "b": [2, 1], "l": [20, 30],
                                   "r": [100, 200]}

    def test_name_collision_suffixes(self):
        left = Table.from_pydict({"k": [1], "v": [10]},
                                 dtypes={"k": dt.INT32, "v": dt.INT64})
        right = Table.from_pydict({"k": [1], "v": [99]},
                                  dtypes={"k": dt.INT32, "v": dt.INT64})
        out = ops.join(left, right, on="k")
        assert set(out.names) == {"k", "v_x", "v_y"}

    def test_empty_right_left_join(self):
        left = Table.from_pydict({"k": [1, 2], "l": [10, 20]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table({"k": Column.from_numpy(np.zeros(0, np.int32)),
                       "r": Column.from_numpy(np.zeros(0, np.int64))})
        out = ops.join(left, right, on="k", how="left")
        assert out.to_pydict() == {"k": [1, 2], "l": [10, 20], "r": [None, None]}

    def test_dtype_mismatch_rejected(self):
        left = Table.from_pydict({"k": [1]}, dtypes={"k": dt.INT32})
        right = Table.from_pydict({"k": [1]}, dtypes={"k": dt.INT64})
        with pytest.raises(ValueError, match="dtype mismatch"):
            ops.join(left, right, on="k")

    def test_full_outer_basic(self):
        left = Table.from_pydict({"k": [1, 2, 3], "l": [10, 20, 30]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [2, 4], "r": [200, 400]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k", how="full")
        # Expansion rows first (left order), then unmatched right; the
        # deduplicated key is coalesced from the right for the tail.
        assert out.to_pydict() == {"k": [1, 2, 3, 4],
                                   "l": [10, 20, 30, None],
                                   "r": [None, 200, None, 400]}

    def test_right_outer_basic(self):
        left = Table.from_pydict({"k": [1, 2, 3], "l": [10, 20, 30]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [2, 4], "r": [200, 400]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k", how="right")
        assert out.to_pydict() == {"k": [2, 4], "l": [20, None],
                                   "r": [200, 400]}

    def test_outer_null_keys_unmatched_both_sides(self):
        # Null keys never match; full outer surfaces them as unmatched
        # rows from BOTH sides (the Spark/cuDF contract pandas breaks —
        # pandas matches NaN keys to each other).
        left = Table.from_pydict({"k": [1, None], "l": [10, 20]},
                                 dtypes={"k": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"k": [None, 1], "r": [100, 200]},
                                  dtypes={"k": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, on="k", how="full")
        assert out.to_pydict() == {"k": [1, None, None],
                                   "l": [10, 20, None],
                                   "r": [200, None, 100]}

    def test_full_outer_distinct_key_names(self):
        # left_on/right_on: both key columns survive; no coalescing.
        left = Table.from_pydict({"lk": [1, 2], "l": [10, 20]},
                                 dtypes={"lk": dt.INT32, "l": dt.INT64})
        right = Table.from_pydict({"rk": [2, 4], "r": [200, 400]},
                                  dtypes={"rk": dt.INT32, "r": dt.INT64})
        out = ops.join(left, right, left_on=["lk"], right_on=["rk"],
                       how="full")
        assert out.to_pydict() == {"lk": [1, 2, None],
                                   "l": [10, 20, None],
                                   "rk": [None, 2, 4],
                                   "r": [None, 200, 400]}

    def test_full_outer_string_payloads(self):
        left = Table.from_pydict({"k": [1, 2], "ls": ["a", None]},
                                 dtypes={"k": dt.INT64, "ls": dt.STRING})
        right = Table.from_pydict({"k": [2, 9], "rs": ["bb", "zz"]},
                                  dtypes={"k": dt.INT64, "rs": dt.STRING})
        out = ops.join(left, right, on="k", how="full")
        assert out.to_pydict() == {"k": [1, 2, 9],
                                   "ls": ["a", None, None],
                                   "rs": [None, "bb", "zz"]}

    def test_outer_random_sweep_vs_oracle(self, rng):
        # Dict-based oracle with Spark null semantics (nulls never match).
        n, m, hi = 400, 350, 50
        lk = [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(0, hi, n)]
        rk = [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(0, hi, m)]
        lv = list(range(n))
        rv = [x * 10 for x in range(m)]
        left = Table.from_pydict({"k": lk, "lv": lv},
                                 dtypes={"k": dt.INT64, "lv": dt.INT64})
        right = Table.from_pydict({"k": rk, "rv": rv},
                                  dtypes={"k": dt.INT64, "rv": dt.INT64})

        def oracle(how):
            rows = []
            rmatched = [False] * m
            for i, k in enumerate(lk):
                matches = [j for j, kr in enumerate(rk)
                           if k is not None and kr == k]
                for j in matches:
                    rmatched[j] = True
                    rows.append((k, lv[i], rv[j]))
                if not matches and how in ("left", "full"):
                    rows.append((k, lv[i], None))
            if how in ("right", "full"):
                for j in range(m):
                    if not rmatched[j]:
                        rows.append((rk[j], None, rv[j]))
            return rows

        def rowkey(r):
            return tuple((x is None, x) for x in r)

        for how in ("inner", "left", "right", "full"):
            got = ops.join(left, right, on="k", how=how).to_pydict()
            got_rows = list(zip(got["k"], got["lv"], got["rv"]))
            assert (sorted(got_rows, key=rowkey)
                    == sorted(oracle(how), key=rowkey)), how

    def test_random_sweep_vs_pandas(self, rng):
        n = 500
        lk = rng.integers(0, 60, n).astype(np.int64)
        rk = rng.integers(0, 60, n).astype(np.int64)
        lv = np.arange(n, dtype=np.int64)
        rv = np.arange(n, dtype=np.int64) * 10
        left = Table.from_pydict({"k": lk.tolist(), "lv": lv.tolist()},
                                 dtypes={"k": dt.INT64, "lv": dt.INT64})
        right = Table.from_pydict({"k": rk.tolist(), "rv": rv.tolist()},
                                  dtypes={"k": dt.INT64, "rv": dt.INT64})
        got = ops.join(left, right, on="k").to_pydict()
        exp = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                       pd.DataFrame({"k": rk, "rv": rv}), on="k", how="inner")
        # compare as sorted multisets of rows
        got_rows = sorted(zip(got["k"], got["lv"], got["rv"]))
        exp_rows = sorted(zip(exp["k"], exp["lv"], exp["rv"]))
        assert got_rows == exp_rows


class TestNaNKeys:
    def test_nan_groups_together(self):
        t = Table.from_pydict({"k": [float("nan"), float("nan"), 1.0],
                               "v": [1, 2, 3]},
                              dtypes={"k": dt.FLOAT64, "v": dt.INT64})
        out = ops.groupby(t, "k").agg({"v": "sum"})
        assert out.num_rows == 2
        assert out.to_pydict()["v"] == [3, 3]   # 1.0 group, NaN group

    def test_nan_keys_join(self):
        left = Table.from_pydict({"k": [float("nan")], "l": [1]},
                                 dtypes={"k": dt.FLOAT64, "l": dt.INT64})
        right = Table.from_pydict({"k": [float("nan")], "r": [2]},
                                  dtypes={"k": dt.FLOAT64, "r": dt.INT64})
        out = ops.join(left, right, on="k")
        assert out.num_rows == 1


class TestStringKeys:
    def test_sort_by_string(self):
        t = Table.from_pydict({"s": ["pear", None, "apple", "fig"]})
        assert ops.sort_by(t, "s").to_pydict()["s"] == [None, "apple", "fig", "pear"]

    def test_groupby_string_key(self):
        t = Table.from_pydict({"s": ["b", "a", "b", None], "v": [1, 2, 3, 4]},
                              dtypes={"s": dt.STRING, "v": dt.INT64})
        out = ops.groupby(t, "s").agg({"v": "sum"})
        assert out.to_pydict() == {"s": [None, "a", "b"], "v": [4, 2, 4]}

    def test_join_string_key(self):
        left = Table.from_pydict({"s": ["x", "y"], "l": [1, 2]},
                                 dtypes={"s": dt.STRING, "l": dt.INT64})
        right = Table.from_pydict({"s": ["y", "z"], "r": [20, 30]},
                                  dtypes={"s": dt.STRING, "r": dt.INT64})
        out = ops.join(left, right, on="s")
        assert out.to_pydict() == {"s": ["y"], "l": [2], "r": [20]}

    def test_fill_null_strings(self):
        c = Column.from_pylist(["a", None, "c"], dt.STRING)
        assert ops.fill_null(c, "x").to_pylist() == ["a", "x", "c"]

    def test_groupby_string_value_count_first_last(self):
        t = Table.from_pydict({"k": [1, 1, 2], "s": ["a", None, "b"]},
                              dtypes={"k": dt.INT64, "s": dt.STRING})
        out = ops.groupby_agg(t, ["k"], [("s", "count", "c"),
                                         ("s", "count_all", "ca"),
                                         ("s", "first", "f"),
                                         ("s", "last", "l")])
        assert out["c"].to_pylist() == [1, 1]
        assert out["ca"].to_pylist() == [2, 1]
        assert out["f"].to_pylist() == ["a", "b"]
        assert out["l"].to_pylist() == [None, "b"]

    def test_groupby_string_value_sum_rejected(self):
        t = Table.from_pydict({"k": [1], "s": ["a"]},
                              dtypes={"k": dt.INT64, "s": dt.STRING})
        import pytest
        with pytest.raises(TypeError):
            ops.groupby_agg(t, ["k"], [("s", "sum", "x")])


class TestDecimalSemantics:
    def test_groupby_mean_applies_scale(self):
        t = Table.from_pydict({"k": [1, 1], "v": [100, 200]},
                              dtypes={"k": dt.INT32, "v": dt.decimal64(-2)})
        out = ops.groupby(t, "k").agg({"v": "mean"})
        assert out.to_pydict()["v"] == [1.5]

    def test_reduction_sum_mean_apply_scale(self):
        c = Column.from_pylist([100, 200], dt.decimal64(-2))
        assert reductions.sum(c) == 3.0
        assert reductions.mean(c) == 1.5

    def test_decimal_scalar_rejected(self):
        a = Column.from_pylist([123], dt.decimal64(-2))
        with pytest.raises(ValueError, match="decimal"):
            ops.binary_op(a, 1, "add")

    def test_decimal_mixed_scale_compare_rejected(self):
        a = Column.from_pylist([123], dt.decimal64(-2))
        b = Column.from_pylist([123], dt.decimal64(-1))
        with pytest.raises(ValueError, match="matching scales"):
            ops.binary_op(a, b, "eq")

    def test_decimal_division_applies_scales(self):
        a = Column.from_pylist([100], dt.decimal64(-2))   # 1.00
        b = Column.from_pylist([2], dt.decimal64(0))      # 2
        assert ops.binary_op(a, b, "truediv").to_pylist() == [0.5]

    def test_uint64_sum_no_wrap(self):
        c = Column.from_pylist([2**63, 2**63 - 1], dt.UINT64)
        assert reductions.sum(c) == 2**64 - 1


class TestReductions:
    def test_basic(self):
        c = Column.from_pylist([1, None, 3], dt.INT64)
        assert reductions.sum(c) == 4
        assert reductions.count(c) == 2
        assert reductions.minimum(c) == 1
        assert reductions.maximum(c) == 3
        assert reductions.mean(c) == 2.0

    def test_all_null_returns_none(self):
        c = Column.from_pylist([None, None], dt.INT64)
        assert reductions.sum(c) is None
        assert reductions.minimum(c) is None
        assert reductions.mean(c) is None
