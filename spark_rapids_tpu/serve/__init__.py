"""Concurrent query serving layer — scheduler, admission control, and
cross-session result caching.

Everything below this package executes ONE query at a time: the
executors (``run_plan``, ``run_plan_stream``, ``run_plan_dist``,
``run_plan_dist_stream``) assume exclusive use of the device, and the
shared program LRUs were, until this layer, guarded only by the GIL.
This package is the multi-tenant layer on top:

* :class:`~.scheduler.QuerySession` / :func:`submit` — admit many
  independent plans at once (``SRT_SERVE_MAX_CONCURRENT`` worker
  threads), interleaving their per-batch dispatches through the
  streaming executors' ``on_dispatch`` fairness gate (round-robin or
  weighted-fair, ``SRT_SERVE_POLICY``) while reusing the donation-safe
  machinery of exec/stream.py unchanged — results stay bit-identical
  to running the same plans sequentially.
* :mod:`~.admission` — per-query HBM budgeting
  (``SRT_SERVE_HBM_BUDGET``) fed by the per-fingerprint cost-ledger
  history: a query whose estimated peak would over-commit the budget
  waits in the queue instead of triggering the OOM recovery ladder
  (which stays on as the backstop).
* :mod:`~.result_cache` — a cross-query result cache
  (``SRT_RESULT_CACHE``) keyed by plan fingerprint + input identity for
  repeated dashboard-style queries.
* :mod:`~.semantic` — a semantic subplan cache
  (``SRT_SEMANTIC_CACHE``): cross-ticket common-subexpression
  elimination over shared plan prefixes, with materialized results
  spliced back into concurrent queries and hit-rate feedback to the
  workload advisor.

Per the repo's lazy-import rule the whole package is jax-free at module
load; executors are imported inside worker threads at first use.
"""

from __future__ import annotations

from .admission import AdmissionController, AdmissionRejected
from .result_cache import ResultCache, input_digest
from .scheduler import QuerySession, Ticket, default_session, submit
from .semantic import SemanticCache, run_table_plan

__all__ = [
    "AdmissionController", "AdmissionRejected", "QuerySession",
    "ResultCache", "SemanticCache", "Ticket", "default_session",
    "input_digest", "run_table_plan", "submit",
]
