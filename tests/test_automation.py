"""Offline tests for the repo-automation layer (bots, pins guard, DCO gate).

The reference tests none of its automation; here the decision logic is
factored into pure functions precisely so it can be covered without a
network or a GitHub token (SURVEY.md §2.2 components: submodule guard,
submodule-sync/auto-merge/cleanup bots, signoff check).
"""

import importlib.util
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _load(path: Path, name: str):
    spec = importlib.util.spec_from_loader(
        name, importlib.machinery.SourceFileLoader(name, str(path)))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


pins_check = _load(REPO / "buildtools" / "pins-check", "pins_check")
ghapi = _load(REPO / ".github/workflows/action-helper/python/ghapi.py",
              "ghapi")
signoff = _load(REPO / ".github/workflows/signoff-check/signoff-check",
                "signoff_check")


class TestPinsCheck:
    def test_current_environment_is_pinned(self):
        # The committed pins must match the CI environment (this IS the
        # guard the reference wires into every build).
        rc = subprocess.run(
            [sys.executable, str(REPO / "buildtools" / "pins-check")],
            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stdout + rc.stderr

    def test_classify_exact(self):
        assert pins_check.classify_drift("1.2.3", "1.2.3", "exact") == "ok"
        assert pins_check.classify_drift("1.2.3", "1.2.4", "exact") == "fail"
        assert pins_check.classify_drift("1.2.3", None, "exact") == "fail"

    def test_classify_minor(self):
        assert pins_check.classify_drift("1.2.3", "1.2.9", "minor") == "warn"
        assert pins_check.classify_drift("1.2.3", "1.3.0", "minor") == "fail"

    def test_drift_detected_and_write_fixes(self, tmp_path):
        pins = tmp_path / "pins.toml"
        pins.write_text('[pins]\nnumpy = "0.0.1"\n\n'
                        '[policy]\nmode = "exact"\n')
        rows = pins_check.check(*pins_check.load_pins(pins))
        assert rows[0][3] == "fail"
        assert pins_check.write_pins(pins) is True
        rows = pins_check.check(*pins_check.load_pins(pins))
        assert rows[0][3] == "ok"
        assert pins_check.write_pins(pins) is False    # idempotent

    def test_skip_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("SRT_PINS_CHECK_SKIP", "1")
        assert pins_check.main(["--pins", str(tmp_path / "nope.toml")]) == 0

    def test_unreadable_pins(self, tmp_path):
        assert pins_check.main(["--pins", str(tmp_path / "nope.toml")]) == 2


class TestGhApiLogic:
    def test_strtobool(self):
        assert ghapi.strtobool("True") and ghapi.strtobool("1")
        assert not ghapi.strtobool("off")
        with pytest.raises(ValueError):
            ghapi.strtobool("maybe")

    def test_pick_existing_pr(self):
        prs = [
            {"head": {"ref": "bot-x"}, "base": {"ref": "main"},
             "state": "open", "number": 1},
            {"head": {"ref": "bot-y"}, "base": {"ref": "main"},
             "state": "open", "number": 2},
        ]
        assert ghapi.pick_existing_pr(prs, "bot-y", "main")["number"] == 2
        assert ghapi.pick_existing_pr(prs, "bot-z", "main") is None
        assert ghapi.pick_existing_pr(prs, "bot-x", "branch-26.10") is None

    def test_should_auto_merge_gate(self):
        # Merge only on green AND sha-consistency (tested == pushed).
        assert ghapi.should_auto_merge(True, "abc", "abc")
        assert not ghapi.should_auto_merge(False, "abc", "abc")
        assert not ghapi.should_auto_merge(True, "abc", "def")
        assert not ghapi.should_auto_merge(True, "", "")


class TestCleanupBot:
    def test_stale_branch_selection(self):
        cleanup = _load(
            REPO / ".github/workflows/action-helper/python/cleanup-bot-branch",
            "cleanup_bot")
        out = cleanup.stale_branches(
            ["bot-deps-sync-main", "bot-auto-merge-x", "bot-live"],
            open_head_refs={"bot-live"})
        assert out == ["bot-deps-sync-main", "bot-auto-merge-x"]


class TestSignoffCheck:
    def test_signed(self):
        msgs = ["Fix thing\n\nSigned-off-by: Dev One <dev@example.com>"]
        assert signoff.unsigned_commits(msgs) == []

    def test_unsigned_and_malformed(self):
        msgs = [
            "no signoff at all",
            "Signed-off-by: missing email",
            "ok\nSigned-off-by: Dev <d@e.io>",
            None,
        ]
        assert signoff.unsigned_commits(msgs) == [0, 1, 3]


class TestCiScripts:
    def test_shell_syntax(self):
        for script in list((REPO / "ci").glob("*.sh")) + [
                REPO / "buildtools" / "build-in-docker",
                REPO / ".github/workflows/action-helper/entrypoint.sh"]:
            rc = subprocess.run(["bash", "-n", str(script)],
                                capture_output=True, text=True)
            assert rc.returncode == 0, f"{script}: {rc.stderr}"

    def test_workflow_yaml_parses(self):
        yaml = pytest.importorskip("yaml")
        for wf in (REPO / ".github/workflows").glob("*.yml"):
            data = yaml.safe_load(wf.read_text())
            assert "jobs" in data, wf
