"""Perf-regression harness over the metrics history.

The history sink (obs/history.py, ``SRT_METRICS_HISTORY=path``) appends
one JSONL QueryMetrics record per finished plan, keyed by plan
fingerprint.  This module turns that file into a gate: for every
fingerprint with at least two records, the LAST record is "the fresh
run" and every earlier record is baseline.  The baseline value for a
metric is the **minimum** over the earlier records — the best prior run
— which makes the gate robust to a slow outlier in history (a cold
compile, a faulted run) while still catching a fresh run that got
slower than the plan has ever been, beyond tolerance.

A breach means ``fresh > best_baseline * (1 + SRT_REGRESS_TOL)``.  The
default gated metrics are wall time, the host-sync count (deterministic
— a new sync is a code regression, not noise), and peak HBM; zero or
missing baselines are skipped, so CPU runs (no allocator stats) gate on
time and syncs only.

Consumers: ``bench_queries.py --regress`` (emits the report as a bench
line and exits nonzero on breaches) and the ci/premerge-build.sh
regression-gate lane (calls :func:`gate`, which raises
:class:`RegressionError`).

No jax at module load (lazy-import rule, see obs/metrics.py).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

from ..config import regress_tolerance
from . import history

#: Dotted key paths into a history record gated by default.
DEFAULT_METRICS: Sequence[str] = (
    "timings.total_seconds",
    "host.syncs",
    "cost.hbm.peak_bytes",
)


class RegressionError(RuntimeError):
    """A fresh run's ledger breached the history baseline."""

    def __init__(self, breaches: List[dict], report: dict) -> None:
        self.breaches = breaches
        self.report = report
        parts = ", ".join(
            f"{b['metric']}[{b.get('fingerprint', '?')}] "
            f"{b['baseline']:g} -> {b['fresh']:g} (x{b['ratio']:g})"
            for b in breaches)
        super().__init__(
            f"{len(breaches)} perf regression(s) vs history baseline "
            f"(tol={report.get('tolerance')}): {parts}")


def _lookup(rec: dict, path: str) -> Optional[float]:
    cur: object = rec
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def compare(fresh: dict, baseline: Iterable[dict], tolerance: float,
            metrics: Sequence[str] = DEFAULT_METRICS) -> List[dict]:
    """Breaches of ``fresh`` against the per-metric min over ``baseline``
    records.  Metrics missing from the fresh record or with no positive
    baseline are skipped (absence is a schema drift, not a perf fact)."""
    baseline = list(baseline)
    breaches: List[dict] = []
    for metric in metrics:
        base_vals = [v for v in (_lookup(r, metric) for r in baseline)
                     if v is not None and v > 0]
        if not base_vals:
            continue
        base = min(base_vals)
        got = _lookup(fresh, metric)
        if got is None:
            continue
        if got > base * (1.0 + tolerance):
            breaches.append({
                "metric": metric,
                "baseline": round(base, 6),
                "fresh": round(got, 6),
                "ratio": round(got / base, 4),
            })
    return breaches


def check_history(path: Optional[str] = None,
                  tolerance: Optional[float] = None,
                  metrics: Sequence[str] = DEFAULT_METRICS) -> dict:
    """The regression report over the history file (default:
    ``SRT_METRICS_HISTORY``): every fingerprint with >= 2 records is
    checked, last record vs the rest.  Never raises on breaches — that
    is :func:`gate`'s job — so ``--regress`` can emit the report line
    before deciding the exit code."""
    if tolerance is None:
        tolerance = regress_tolerance()
    records = history.load(path=path)
    by_fp: Dict[str, List[dict]] = {}
    for rec in records:
        fp = rec.get("fingerprint")
        if isinstance(fp, str) and fp:
            by_fp.setdefault(fp, []).append(rec)
    breaches: List[dict] = []
    checked = 0
    for fp, recs in sorted(by_fp.items()):
        if len(recs) < 2:
            continue
        checked += 1
        for b in compare(recs[-1], recs[:-1], tolerance, metrics):
            breaches.append(dict(b, fingerprint=fp))
    return {
        "metric": "regress",
        "tolerance": tolerance,
        "fingerprints": len(by_fp),
        "checked": checked,
        "breaches": breaches,
        "corrupt_lines": history.last_load_skipped(),
    }


def gate(path: Optional[str] = None,
         tolerance: Optional[float] = None,
         metrics: Sequence[str] = DEFAULT_METRICS) -> dict:
    """``check_history`` that raises :class:`RegressionError` on any
    breach; returns the clean report otherwise (the CI lane's entry
    point)."""
    report = check_history(path=path, tolerance=tolerance, metrics=metrics)
    if report["breaches"]:
        raise RegressionError(report["breaches"], report)
    return report
