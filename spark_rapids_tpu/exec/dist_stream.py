"""Sharded streaming executor: one in-flight window per shard,
distributed stream-combine over ICI.

The mesh counterpart of :mod:`.stream`, driven by the same
``run_plan_stream`` entry point via ``mesh=`` (or ``run_plan_dist_stream``
directly).  Each host batch is dealt row-wise over the mesh with a
per-shard slot capacity snapped to the shared bucket schedule
(:func:`.bucketing.shard_capacity`), so every batch size in one bucket
shares one ``(shards * capacity)`` sharded program shape and every
(bucket, mesh) pair compiles exactly one program in the shared
``_DIST_COMPILED`` LRU.  Up to K batches sit dispatched but
unmaterialized per shard (``SRT_DIST_STREAM_INFLIGHT``, defaulting to
the single-chip ``SRT_STREAM_INFLIGHT``), and the sharded padded copies
are engine-owned by construction (``shard_table`` always builds fresh
buffers), so every dispatch donates them — same-bucket batches recycle
HBM shard-wise.

Two modes, matching the single-chip driver:

* **per-batch** — yields one Table per input batch, bit-identical to the
  single-chip ``run_plan_stream``: row-local plans collect each batch's
  row-sharded result (the contiguous deal-out preserves row order),
  group-by plans materialize the replicated per-batch merge.
* **streaming combine** — per-shard dense partial accumulators
  (``exec.dist._dist_partial_program``, stacked ``(shards, cells)`` and
  row-sharded) fold across batches in the existing binomial tree with
  zero per-batch ICI, then ONE psum/psum-gather merge collective
  (``compile.stream_merge_cells`` under ``shard_map``) and ONE
  materialize close the stream — ICI traffic is O(1) per stream instead
  of O(batches).

Live-row counts ride on device across batches (``DistTable.
live_count_device``) and sync once at stream end; the per-dispatch
``dist.live_count`` syncs the batch-at-a-time dist path pays are
recorded as avoided (``utils.memory.record_avoided_sync``), so
``host_syncs`` visibly drops in QueryMetrics.

Every phase runs under ``oom_ladder(dist=True)`` with a drain hook that
materializes the per-shard in-flight window first; the split rung reuses
the mesh ladder's per-shard halving (``exec.dist._dist_split`` /
``_shard_slice``), preserving output order and the combine carry, so
faulted sharded streams stay bit-identical to fault-free runs.
"""

from __future__ import annotations

import time as _time
import warnings
from collections import deque

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from functools import partial

from ..parallel.mesh import (DistTable, collect, mesh_cache_key, record_ici,
                             shard_map, shard_table)
from ..table import Table
from .bucketing import bucket_capacity, shard_capacity
from .compile import (_Bound, _final_order, _lru_lookup, materialize,
                      run_plan_eager, stream_combine, stream_finalize,
                      stream_merge_cells)
from .dist import (_DIST_COMPILED, _build_dist_program, _dist_partial_program,
                   _dist_split, _execute_dist_resilient, _shard_slice)
from .plan import GroupAggStep, JoinShuffledStep
from .stream import _chain_batches, _combine_setup


def _shard_batch(batch: Table, mesh, plan=None) -> DistTable:
    """Deal one host batch over the mesh at the shared bucket schedule's
    per-shard capacity.  The returned DistTable's buffers are fresh
    engine-owned copies — never the caller's — so they are always safe
    to donate.

    When ``plan`` is an optimizer-pruned plan, the batch is subset to
    its live input columns BEFORE the deal-out — pruned payload columns
    never pad, ship over ICI, or pin per-shard HBM."""
    if plan is not None:
        from .compile import _pruned_input
        batch = _pruned_input(plan, batch)
    P = int(mesh.devices.size)
    return shard_table(batch, mesh,
                       capacity=shard_capacity(batch.num_rows, P))


def _check_fixed_width(bound: _Bound) -> None:
    if bound.string_cols or bound.dictionaries:
        raise TypeError(
            "distributed plans operate on fixed-width columns only "
            "(dictionary-encode strings before sharding, as shard_table "
            "requires)")


def _dispatch_donating(fn, bound, row_mask):
    """Invoke a donating sharded program; report whether the per-shard
    input buffers were actually reclaimed (see stream._dispatch_donated
    — aggregation-terminated programs emit cells-shaped outputs, so
    their inputs survive and the backend warns; keep the stream quiet
    and let the ``is_deleted`` probe tell the truth)."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message=".*[Dd]onat.*", category=UserWarning)
        out = fn(bound.exec_cols, row_mask, bound.side_inputs)
    consumed = any(c.is_deleted() for c in bound.exec_cols.values())
    return out, consumed


def _account_donation(acct, reclaimed: bool, lane: str, bi: int) -> None:
    from ..obs.metrics import counter
    from ..obs.timeline import instant as _tinstant
    if reclaimed:
        acct.donation_hits += 1
        counter("stream.donation.hit").inc()
        _tinstant("stream.donation.hit", cat="stream", lane=lane, batch=bi)
    else:
        acct.donation_misses += 1
        counter("stream.donation.miss").inc()
        _tinstant("stream.donation.miss", cat="stream", lane=lane, batch=bi)
    acct.live.donation(reclaimed)


def _finish_live_count(acct, live_dev) -> None:
    """The stream's ONE live-count sync: fold the device-carried per-batch
    counts the batch-at-a-time dist path would have synced eagerly."""
    if live_dev is None:
        return
    from ..utils.memory import record_host_sync
    t0 = _time.perf_counter()
    acct.live_rows = int(live_dev)
    record_host_sync("dist.stream.live_count", 8,
                     seconds=_time.perf_counter() - t0)
    acct.live.set_live_rows(acct.live_rows)


def _drive_batches_dist(plan, source, k: int, acct, mesh):
    """Per-batch sharded pipeline: shard → donating sharded dispatch →
    deferred materialize/collect, with up to ``k`` batches in flight per
    shard.  Yields one Table per batch, bit-identical to the single-chip
    per-batch driver (contiguous deal-out + collect preserve row order
    for row-local plans; group-by plans materialize the replicated
    merge).  Recovery drains the in-flight window, then evicts and
    retries; a still-OOMing batch takes the mesh ladder's per-shard
    split rung and rides the deque as a ready result — output order is
    preserved."""
    from ..config import metrics_enabled
    from ..obs.metrics import gauge
    from ..obs.timeline import span as _tspan
    from ..resilience import dist_guard, fault_point
    from ..resilience.classify import ExecutionRecoveryError
    from ..resilience.recovery import SplitUnavailable, oom_ladder
    from ..utils.memory import _tree_nbytes, record_avoided_sync

    axis = mesh.axis_names[0]
    P = int(mesh.devices.size)
    acct.shards = P
    acct.live.set_shards(P)
    meter = metrics_enabled()
    replicated_out = any(isinstance(s, GroupAggStep) for s in plan.steps)
    shuffled = any(isinstance(s, JoinShuffledStep) for s in plan.steps)
    # ("exec", bound, out_cols, sel, bi) | ("res", result, bi) |
    # ("ready", table, bi) — "res" holds a resilient-core result (split
    # rung or shuffled-join batch) whose collect is deferred like any
    # other in-flight entry.
    pending: deque = deque()
    inflight_gauge = gauge("stream.inflight_depth")
    live_dev = None

    def finish_entry(entry):
        if entry[0] == "ready":
            return entry[1]
        if entry[0] == "res":
            result = entry[1]
            if isinstance(result, DistTable):
                return oom_ladder("materialize",
                                  lambda: collect(result), dist=True)
            return result
        _, bound, out_cols, sel, bi = entry
        with _tspan("stream.materialize", cat="stream",
                    lane=f"batch-{bi}", batch=bi, shards=P):
            if replicated_out:
                return oom_ladder(
                    "materialize",
                    lambda: materialize(bound, out_cols, sel), dist=True)
            order = [nm for nm in _final_order(plan.steps,
                                               bound.input_names)
                     if nm in out_cols]
            order += [nm for nm in out_cols if nm not in order]
            dtable = DistTable(
                table=Table([(nm, out_cols[nm]) for nm in order]),
                row_mask=sel.astype(jnp.bool_))
            return oom_ladder("materialize",
                              lambda: collect(dtable), dist=True)

    def drain_inflight():
        """Recovery hook: turn every pending dispatch into a ready host
        Table in place, releasing its per-shard output buffers before
        the ladder retries."""
        for i, entry in enumerate(pending):
            if entry[0] != "ready":
                pending[i] = ("ready", finish_entry(entry), entry[-1])

    def drain_oldest():
        entry = pending.popleft()
        if entry[0] == "ready":
            return entry[1]
        t0 = _time.perf_counter()
        out = finish_entry(entry)
        acct.mat_s += _time.perf_counter() - t0
        return out

    for bi, batch in enumerate(source):
        lane = f"batch-{bi}"
        if batch.num_rows == 0:
            pending.append(("ready", run_plan_eager(plan, batch), bi))
        elif shuffled:
            # Shuffled-join plans route per batch through the resilient
            # dist core (the all_to_all repartition is the work); the
            # known batch size skips its per-dispatch live-count sync.
            if acct.on_dispatch is not None:
                acct.on_dispatch()      # serving fairness gate
            t0 = _time.perf_counter()
            with _tspan("stream.dispatch", cat="stream", lane=lane,
                        batch=bi, shards=P):
                dist_b = _shard_batch(batch, mesh, plan)
                live = dist_b.live_count_device()
                live_dev = live if live_dev is None else live_dev + live
                result = _execute_dist_resilient(
                    plan, dist_b, mesh, live_rows=batch.num_rows)
            acct.syncs_avoided += 1
            acct.dispatch_s += _time.perf_counter() - t0
            pending.append(("res", result, bi))
        else:
            t0 = _time.perf_counter()
            with _tspan("stream.bind", cat="stream", lane=lane, batch=bi,
                        rows=batch.num_rows, shards=P):
                dist_b = _shard_batch(batch, mesh, plan)
                record_avoided_sync("dist.live_count")
                acct.syncs_avoided += 1
                live = dist_b.live_count_device()
                live_dev = live if live_dev is None else live_dev + live
                state = [dist_b, None]      # [DistTable, _Bound]

                def do_bind():
                    fault_point("bind")
                    bound = _Bound(plan, state[0].table,
                                   probe_mask=state[0].row_mask)
                    _check_fixed_width(bound)
                    return bound
                state[1] = oom_ladder("bind", do_bind,
                                      drain=drain_inflight, dist=True)
            acct.bind_s += _time.perf_counter() - t0

            key = (("dist/stream", replicated_out)
                   + state[1].signature() + (mesh_cache_key(mesh),))

            def do_dispatch():
                # A prior attempt may have donated (and lost) this
                # batch's sharded copies — re-shard from the user's
                # batch, which is never donated.
                if any(c.is_deleted()
                       for c in state[1].exec_cols.values()):
                    state[0] = _shard_batch(batch, mesh, plan)
                    state[1] = _Bound(plan, state[0].table,
                                      probe_mask=state[0].row_mask)
                # Looked up INSIDE the ladder closure: an evict rung
                # clears the LRU, so a retry rebuilds.
                fn, _ = _lru_lookup(
                    _DIST_COMPILED, key,
                    lambda: _build_dist_program(
                        state[1], mesh, axis, P, replicated_out,
                        donate=True),
                    "dist.compile_cache", shards=P)

                def invoke():
                    for s in range(P):
                        fault_point("dist-dispatch", shard=s)
                    if replicated_out:
                        for s in range(P):
                            fault_point("collective", shard=s)
                    return _dispatch_donating(fn, state[1],
                                              state[0].row_mask)
                return dist_guard("dist.dispatch", invoke)

            if acct.on_dispatch is not None:
                acct.on_dispatch()      # serving fairness gate
            t0 = _time.perf_counter()
            try:
                with _tspan("stream.dispatch", cat="stream", lane=lane,
                            batch=bi, shards=P):
                    (out_cols, sel), reclaimed = oom_ladder(
                        "dist-dispatch", do_dispatch,
                        drain=drain_inflight, dist=True)
            except ExecutionRecoveryError as err:
                if err.category != "oom":
                    raise
                try:    # last rung: per-shard split, ride as a result
                    with _tspan("stream.split", cat="stream", lane=lane,
                                batch=bi, shards=P):
                        pending.append(
                            ("res", _dist_split(plan, state[0], mesh, 0),
                             bi))
                except SplitUnavailable as unavailable:
                    err.add_step(f"split-unavailable: {unavailable}")
                    # Graceful degradation, mirroring the core dist
                    # ladder: finish this batch single-chip when
                    # SRT_DIST_FALLBACK=collect opts in.
                    from ..config import dist_fallback
                    if dist_fallback() is None:
                        err.add_step("collect-fallback: disabled "
                                     "(SRT_DIST_FALLBACK unset)")
                        raise err
                    from ..resilience import recovery_stats
                    from .compile import run_plan
                    recovery_stats().add_dist_fallback()
                    err.add_step("collect-fallback")
                    pending.append(("ready", run_plan(plan, batch), bi))
                acct.dispatch_s += _time.perf_counter() - t0
            else:
                _account_donation(acct, reclaimed, lane, bi)
                if replicated_out:
                    acct.merge_collectives += 1
                    if meter:
                        ici_bytes = 2 * (P - 1) * _tree_nbytes(out_cols)
                        record_ici(ici_bytes)
                        acct.ici_bytes += ici_bytes
                acct.dispatch_s += _time.perf_counter() - t0
                pending.append(("exec", state[1], out_cols, sel, bi))
        if batch.num_rows:
            acct.live.shard_batches_done(P)
        while len(pending) > k:
            yield drain_oldest()
        depth = sum(1 for e in pending if e[0] != "ready")
        acct.live.set_inflight(depth)
        if depth > acct.peak_inflight:
            acct.peak_inflight = depth
            inflight_gauge.set(depth)
    while pending:
        yield drain_oldest()
    _finish_live_count(acct, live_dev)


def _drive_combine_dist(plan, source, k: int, acct, mesh, strict: bool):
    """Sharded streaming combine: per batch, a donating sharded
    partial-aggregate program folds the shard-local rows into stacked
    ``(shards, cells)`` accumulators (NO collective); batches merge in
    the binomial tree shard-locally; at stream end ONE psum/psum-gather
    merge collective replicates the totals and ONE materialize closes
    the stream.  Falls back to the per-batch sharded driver when the
    first bind shows the layout cannot be batch-invariant — unless
    ``strict``."""
    from ..config import metrics_enabled
    from ..obs import timeline as _tl
    from ..obs.metrics import gauge
    from ..obs.timeline import span as _tspan
    from ..resilience import dist_guard, fault_point, recovery_stats
    from ..resilience.classify import ExecutionRecoveryError
    from ..resilience.recovery import SplitUnavailable, oom_ladder
    from ..utils.memory import _tree_nbytes, record_avoided_sync

    axis = mesh.axis_names[0]
    P = int(mesh.devices.size)
    acct.shards = P
    acct.live.set_shards(P)
    meter = metrics_enabled()
    levels: list = []           # levels[i]: acc of 2^i batches, or None
    bound0 = smeta = dtypes = None
    last_empty = None
    consumed: list = []         # batches seen before viability is decided
    since_block = 0
    live_dev = None
    inflight_gauge = gauge("stream.inflight_depth")

    def drain_levels():
        """Recovery hook: force the whole per-shard accumulator tree to
        finish so its transient dispatch scratch frees before a retry.
        Skips buffers the donating cell-merge already consumed."""
        live = [a for lv in levels if lv is not None
                for a in lv.values() if not a.is_deleted()]
        if live:
            jax.block_until_ready(live)

    def split_partial(dist_b):
        """Last recovery rung for a combine-mode batch: halve the
        per-shard slot count (snapped to the bucket schedule),
        partial-aggregate each half without donation, and merge into the
        ONE stacked accumulator the batch would have produced — the
        binomial-tree carry downstream is identical to a no-fault run."""
        C = dist_b.capacity_total // P
        if C < 2:
            raise SplitUnavailable(
                f"per-shard capacity of {C} slot(s) cannot split")
        cut = min(bucket_capacity((C + 1) // 2, floor=8), C - 1)
        stats = recovery_stats()
        stats.add_split()
        stats.add_dist_split()
        accs = []
        for lo, hi in ((0, cut), (cut, C)):
            piece = _shard_slice(dist_b, P, C, lo, hi)
            b = oom_ladder(
                "bind",
                lambda p=piece: _Bound(plan, p.table,
                                       probe_mask=p.row_mask),
                drain=drain_levels, dist=True)

            def do_piece(b=b, rm=piece.row_mask):
                fn = _dist_partial_program(b, smeta, mesh, axis)
                return fn(b.exec_cols, rm, b.side_inputs)

            accs.append(oom_ladder("dist-dispatch", do_piece,
                                   drain=drain_levels, dist=True))
        return stream_combine()(accs[0], accs[1])

    for bi, batch in enumerate(source):
        lane = f"batch-{bi}"
        if smeta is None:
            consumed.append(batch)
        if batch.num_rows == 0:
            last_empty = batch          # contributes no groups
            continue
        t0 = _time.perf_counter()
        with _tspan("stream.bind", cat="stream", lane=lane, batch=bi,
                    rows=batch.num_rows, shards=P):
            dist_b = _shard_batch(batch, mesh, plan)
            state = [dist_b, None]

            def do_bind():
                fault_point("bind")
                bound = _Bound(plan, state[0].table,
                               probe_mask=state[0].row_mask)
                _check_fixed_width(bound)
                return bound
            state[1] = oom_ladder("bind", do_bind, drain=drain_levels,
                                  dist=True)
        acct.bind_s += _time.perf_counter() - t0
        if smeta is None:
            try:
                smeta, dtypes = _combine_setup(state[1])
            except TypeError:
                if strict:
                    raise
                # The layout is not batch-invariant: replay everything
                # consumed so far (leading empties included, in order)
                # through the per-batch sharded driver instead.
                yield from _drive_batches_dist(
                    plan, _chain_batches(consumed, source), k, acct, mesh)
                return
            bound0 = state[1]
            consumed.clear()
        # Accounted only once viability is settled, so a combine->
        # per-batch fallback never double-counts the replayed batch.
        record_avoided_sync("dist.live_count")
        acct.syncs_avoided += 1
        live = state[0].live_count_device()
        live_dev = live if live_dev is None else live_dev + live

        def do_partial():
            # A prior attempt may have donated (and lost) this batch's
            # sharded copies — re-shard from the user's batch.
            if any(c.is_deleted() for c in state[1].exec_cols.values()):
                state[0] = _shard_batch(batch, mesh, plan)
                state[1] = _Bound(plan, state[0].table,
                                  probe_mask=state[0].row_mask)
            fn = _dist_partial_program(state[1], smeta, mesh, axis,
                                       donate=True)

            def invoke():
                for s in range(P):
                    fault_point("dist-dispatch", shard=s)
                return _dispatch_donating(fn, state[1],
                                          state[0].row_mask)
            return dist_guard("dist.dispatch", invoke)

        if acct.on_dispatch is not None:
            acct.on_dispatch()          # serving fairness gate
        t0 = _time.perf_counter()
        try:
            with _tspan("stream.partial", cat="stream", lane=lane,
                        batch=bi, shards=P):
                acc, reclaimed = oom_ladder(
                    "dist-dispatch", do_partial, drain=drain_levels,
                    dist=True)
        except ExecutionRecoveryError as err:
            if err.category != "oom":
                raise
            try:
                with _tspan("stream.split", cat="stream", lane=lane,
                            batch=bi, shards=P):
                    acc = split_partial(state[0])
            except SplitUnavailable as unavailable:
                err.add_step(f"split-unavailable: {unavailable}")
                raise err
            reclaimed = False
        _account_donation(acct, reclaimed, lane, bi)
        merge = stream_combine()
        i = 0
        while i < len(levels) and levels[i] is not None:
            lv, acc_in = levels[i], acc
            with _tspan("stream.combine", cat="stream", lane="combine",
                        level=i, batch=bi):
                acc = oom_ladder(
                    "stream-combine",
                    lambda lv=lv, a=acc_in: (fault_point("stream-combine"),
                                             merge(lv, a))[1],
                    drain=drain_levels, dist=True)
            levels[i] = None
            i += 1
        if i == len(levels):
            levels.append(acc)
        else:
            levels[i] = acc
        acct.dispatch_s += _time.perf_counter() - t0
        acct.live.shard_batches_done(P)
        since_block += 1
        acct.live.set_inflight(since_block)
        if since_block > acct.peak_inflight:
            acct.peak_inflight = since_block
            inflight_gauge.set(since_block)
        if since_block >= k:
            with _tspan("stream.backpressure", cat="stream",
                        lane="combine", level=i):
                jax.block_until_ready(levels[i])
            since_block = 0

    if smeta is None:
        if last_empty is not None:      # schema known, zero groups
            yield run_plan_eager(plan, last_empty)
        return
    total = None
    merge = stream_combine()
    for li, lv in enumerate(levels):
        if lv is None:
            continue
        levels[li] = None   # consumed below (merge donates its first arg)
        if total is None:
            total = lv
            continue
        t, l = total, lv
        with _tspan("stream.combine", cat="stream", lane="combine"):
            total = oom_ladder(
                "stream-combine",
                lambda t=t, l=l: (fault_point("stream-combine"),
                                  merge(t, l))[1],
                drain=drain_levels, dist=True)

    # The stream's ONE merge collective: replicate the per-shard totals.
    shapes = tuple(sorted((name, tuple(v.shape), str(v.dtype))
                          for name, v in total.items()))
    mkey = ("dist/stream-merge", shapes, mesh_cache_key(mesh))
    total_holder = [total]

    def do_merge():
        fn, _ = _lru_lookup(
            _DIST_COMPILED, mkey,
            lambda: jax.jit(partial(
                shard_map, mesh=mesh, in_specs=(PartitionSpec(axis),),
                out_specs=PartitionSpec(), check_vma=False,
            )(lambda acc: stream_merge_cells(acc, axis, P))),
            "dist.compile_cache", shards=P)

        def invoke():
            for s in range(P):
                fault_point("collective", shard=s)
            return jax.block_until_ready(fn(total_holder[0]))
        return dist_guard("dist.merge", invoke)

    acct.live.set_phase("merge-collective")
    t0 = _time.perf_counter()
    tl_on = _tl.enabled()
    t_us = _tl.now_us() if tl_on else 0.0
    with _tspan("stream.merge_collective", cat="stream", lane="combine",
                shards=P):
        merged = oom_ladder("collective", do_merge, drain=drain_levels,
                            dist=True)
    dur_s = _time.perf_counter() - t0
    acct.dispatch_s += dur_s
    acct.merge_collectives += 1
    ici_bytes = 2 * (P - 1) * _tree_nbytes(merged)
    acct.ici_bytes += ici_bytes
    if meter:
        record_ici(ici_bytes, seconds=dur_s)
    if tl_on:
        # SPMD: every shard runs the merge over the same interval — one
        # ici.psum event per shard lane, the stream's whole ICI story.
        dur = _tl.now_us() - t_us
        for s in range(P):
            _tl.add_complete("ici.psum", "ici", t_us, dur,
                             lane=f"shard-{s}", shard=s,
                             collective="psum")

    t0 = _time.perf_counter()
    with _tspan("stream.finalize", cat="stream", lane="combine"):
        out = oom_ladder(
            "materialize",
            lambda: stream_finalize(bound0, smeta, merged, dtypes),
            dist=True)
    acct.mat_s += _time.perf_counter() - t0
    _finish_live_count(acct, live_dev)
    yield out
