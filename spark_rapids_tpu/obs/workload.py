"""Workload intelligence — fleet-wide op hotspots and subplan overlap.

The obs stack explains one query (flight recorder → bundle → doctor)
and one process (capacity accountant), but the two biggest roadmap
bets need evidence about the *workload*: which step kinds dominate the
fleet's cost ledger (ROADMAP item 1 — the next Pallas kernel targets)
and which subplan prefixes recur across queries (ROADMAP item 4 —
fragment-materialization candidates, the Presto-GPU fragment-cache
motivation).  This module mines both from what the stack already
emits:

  * a **query window** — a bounded deque of normalized per-query
    workload records fed at completion (obs/history.maybe_record, which
    has both the optimized plan and the QueryMetrics) plus the
    scheduler's submitted tickets (serve/scheduler.py);
  * **op hotspots**: the per-plan cost ledger aggregated by step kind
    across the window — seconds, bytes, ICI, host syncs per kind, with
    p50/p95 per-row cost from measured (analyze) steps — ranked so the
    top entries name kernel targets with a projected win;
  * **overlap candidates**: optimized plan prefixes (leading
    scan/filter/project/join runs, exec/optimize.prefix_step_texts)
    canonicalized into subplan fingerprints
    (obs/history.subplan_fingerprint), counted for cross-query
    recurrence, and scored as frequency x measured prefix cost x
    estimated result bytes;
  * the same confirm/clear **hysteresis** discipline as the capacity
    advisor (:class:`obs.capacity.Advisor` is reused verbatim), so a
    recommendation only surfaces after consecutive supporting windows.

Contract (mirrors obs/capacity.py):

  * jax-free at import (pinned by an import-hygiene test);
  * off unless ``SRT_METRICS=1`` — every ``feed_*`` returns after one
    env read, and :func:`snapshot` over an unfed window is well-defined
    (no hotspots, no candidates);
  * ``derive`` / ``recommend`` are pure over explicit inputs — the
    mining math is unit-testable without a device, server, or clock.

Surfaces: ``/workload`` + ``srt_workload_*`` gauges (obs/server.py —
scrapes use snapshot()+recommend() and never advance hysteresis), a
workload pane in ``obs top`` and ``python -m spark_rapids_tpu.obs
workload`` (live ``--url``, in-process, or offline ``--history`` over
the reverse reader), and a ``workload`` block in postmortem bundles
(obs/bundle.py → obs/doctor.py findings).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..config import metrics_enabled
from .capacity import Advisor, percentile

__all__ = [
    "KERNEL_SPEEDUP", "HOTSPOT_MIN_SHARE", "HOTSPOT_MIN_SECONDS",
    "OVERLAP_MIN_COUNT", "COLD_SEVERITY_CAP",
    "feed_query", "feed_ticket", "feed_semantic",
    "semantic_stats", "cold_evicted_fps", "set_confirmed_sink",
    "plan_prefixes", "prefixes_from_steps",
    "record_from_history", "records_from_history",
    "derive", "recommend", "Advisor", "verdict_for",
    "window_records", "snapshot", "advise", "bundle_block", "reset",
    "validate_payload", "KERNEL_FOR_KIND", "kernels_block",
]

#: Assumed speedup of a hand-written Pallas kernel over the current XLA
#: lowering for one step kind — the fallback prior when the kernel
#: registry has no measurement yet.  Once ``bench_queries.py --kernels``
#: (or any dispatch site calling ``record_speedup``) has measured the
#: kernel for a kind, the measured ratio replaces this constant in the
#: hotspot's ``projected_win_s``; the ratio actually used is published
#: as the hotspot's ``assumed_speedup``.
KERNEL_SPEEDUP = 2.0

#: Step kind → kernel-registry name, for looking up measured speedups.
#: Kinds absent here (Sort, Filter, ...) have no Pallas kernel yet and
#: keep the :data:`KERNEL_SPEEDUP` prior.
KERNEL_FOR_KIND = {
    "BroadcastJoin": "join",
    "ShuffledJoin": "join",
    "GroupBy[dense]": "groupby",
    "GroupBy[sorted]": "groupby",
    "Scan": "decode",
}

#: A step kind must hold at least this share of attributed step seconds
#: (and this many absolute seconds) before the advisor proposes a
#: kernel for it — tiny windows must not nominate noise.
HOTSPOT_MIN_SHARE = 0.25
HOTSPOT_MIN_SECONDS = 0.02

#: A subplan prefix must recur at least this many times in the window
#: before it is a materialization candidate.
OVERLAP_MIN_COUNT = 2

#: Severity ceiling for a ``materialize_subplan`` recommendation whose
#: prefix was already materialized once and evicted without a single
#: hit (the semantic cache's outcome feed, :func:`feed_semantic`) —
#: evidence the workload does not actually reuse it, so the advisor
#: stops shouting about it (40 < the "suggestive" threshold of 50).
COLD_SEVERITY_CAP = 40

#: Per-row result-size floor (bytes) used when a prefix's output width
#: is unknown — the benefit score only needs a consistent scale.
_EST_BYTES_PER_ROW = 8

# Window retention: same bound-memory discipline as obs/capacity.py.
_MAXEVENTS = 4096

_LOCK = threading.Lock()
#: (t, normalized record) — completed queries.
_QUERIES: "deque[Tuple[float, Dict[str, Any]]]" = deque(maxlen=_MAXEVENTS)
#: (t, plan fingerprint, prefix fingerprints) — submitted tickets.
_TICKETS: "deque[Tuple[float, str, Tuple[str, ...]]]" = deque(
    maxlen=_MAXEVENTS)


def _now() -> float:
    return time.perf_counter()


# ---------------------------------------------------------------------------
# Prefix canonicalization (shared by the live feed and the history sink)
# ---------------------------------------------------------------------------

def _text_kind(text: str) -> str:
    """Step kind from an optimize._step_text ("Filter[x>1]" -> "Filter")."""
    return text.split("[", 1)[0]


def plan_prefixes(plan, qm=None) -> List[Dict[str, Any]]:
    """Canonical subplan prefixes of an **optimized** plan, each scored
    with measured cost when ``qm`` carries per-step observations.

    Returns ``[{fingerprint, depth, kinds, seconds, measured,
    est_result_bytes}]`` — fingerprints from
    ``history.subplan_fingerprint`` over
    ``exec.optimize.prefix_step_texts``, so a live plan, a scheduler
    ticket, and a history record share one hash space.  ``seconds`` is
    the summed measured step seconds over the prefix (analyze runs);
    unmeasured prefixes fall back to a depth-proportional share of
    ``qm.execute_seconds`` with ``measured=False``.  Never raises —
    a plan the prefix walker cannot read yields no prefixes."""
    try:
        from ..exec.optimize import prefix_step_texts
        from .history import subplan_fingerprint
        prefix_texts = prefix_step_texts(plan)
    except Exception:
        return []
    steps = list(getattr(qm, "steps", ()) or ()) if qm is not None else []
    n_steps = max(len(getattr(plan, "steps", ())), 1)
    execute = float(getattr(qm, "execute_seconds", 0.0) or 0.0) \
        if qm is not None else 0.0
    input_rows = int(getattr(qm, "input_rows", 0) or 0) \
        if qm is not None else 0
    out: List[Dict[str, Any]] = []
    for texts in prefix_texts:
        depth = len(texts)
        secs = [s.seconds for s in steps[:depth]
                if getattr(s, "seconds", -1.0) >= 0.0]
        measured = len(secs) == depth and depth > 0
        seconds = sum(secs) if measured \
            else execute * depth / n_steps
        rows_out = -1
        if depth <= len(steps):
            rows_out = int(getattr(steps[depth - 1], "rows_out", -1))
        est_rows = rows_out if rows_out >= 0 else input_rows
        out.append({
            "fingerprint": subplan_fingerprint(texts),
            "depth": depth,
            "kinds": [_text_kind(t) for t in texts],
            "seconds": round(max(seconds, 0.0), 6),
            "measured": bool(measured),
            "est_result_bytes": int(max(est_rows, 0)) * _EST_BYTES_PER_ROW,
        })
    return out


def prefixes_from_steps(steps: Sequence[dict],
                        input_rows: int = 0,
                        execute_seconds: float = 0.0
                        ) -> List[Dict[str, Any]]:
    """Prefix dicts recovered from a history record's ``steps`` list —
    the fallback for records written before the sink embedded
    ``prefixes``.  Canonicalizes over the recorded ``describe`` texts
    (stable for one logical plan, a *different* hash space from
    :func:`plan_prefixes` — old-corpus overlaps still mine correctly
    against each other, just not against new-format records)."""
    from .history import subplan_fingerprint
    lead: List[dict] = []
    for s in steps:
        if not isinstance(s, dict):
            break
        kind = str(s.get("kind") or "")
        if _text_kind(kind) not in ("Filter", "Select", "Project",
                                    "BroadcastJoin", "ShuffledJoin"):
            break
        lead.append(s)
    n_steps = max(len(steps), 1)
    out: List[Dict[str, Any]] = []
    for depth in range(1, len(lead) + 1):
        texts = [str(s.get("describe") or s.get("kind") or "")
                 for s in lead[:depth]]
        secs = [float(s.get("seconds", -1.0)) for s in lead[:depth]]
        measured = all(x >= 0.0 for x in secs) and depth > 0
        seconds = sum(secs) if measured \
            else execute_seconds * depth / n_steps
        rows_out = lead[depth - 1].get("rows_out", -1)
        rows_out = int(rows_out) if isinstance(rows_out, (int, float)) \
            else -1
        est_rows = rows_out if rows_out >= 0 else input_rows
        out.append({
            "fingerprint": subplan_fingerprint(texts),
            "depth": depth,
            "kinds": [_text_kind(str(s.get("kind") or "?"))
                      for s in lead[:depth]],
            "seconds": round(max(seconds, 0.0), 6),
            "measured": bool(measured),
            "est_result_bytes": int(max(est_rows, 0)) * _EST_BYTES_PER_ROW,
        })
    return out


# ---------------------------------------------------------------------------
# Record normalization (one shape for the live feed and offline replay)
# ---------------------------------------------------------------------------

def record_from_history(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """One metrics-history JSONL record (obs/history.py — the
    QueryMetrics.to_dict shape plus the sink's extras) normalized into
    the workload-window record shape, or None for a non-record."""
    if not isinstance(rec, dict):
        return None
    timings = rec.get("timings") or {}
    cost = rec.get("cost") or {}
    analysis = cost.get("analysis") or {}
    host = rec.get("host") or {}
    steps_in = rec.get("steps") or []
    steps = []
    for s in steps_in:
        if not isinstance(s, dict) or not s.get("kind"):
            continue
        steps.append({
            "kind": str(s["kind"]),
            "seconds": float(s.get("seconds", -1.0) or 0.0),
            "rows_in": int(s.get("rows_in", -1) or 0),
            "rows_out": int(s.get("rows_out", -1) or 0),
        })
    execute = float(timings.get("execute_seconds") or 0.0)
    input_rows = int((rec.get("input") or {}).get("rows") or 0)
    prefixes = rec.get("prefixes")
    if not isinstance(prefixes, list):
        prefixes = prefixes_from_steps(steps_in, input_rows=input_rows,
                                       execute_seconds=execute)
    return {
        "fingerprint": str(rec.get("fingerprint") or ""),
        "mode": str(rec.get("mode") or "?"),
        "total_seconds": float(rec.get("total_seconds")
                               or timings.get("total_seconds") or 0.0),
        "execute_seconds": execute,
        "input_rows": input_rows,
        "steps": steps,
        "bytes_accessed": float(analysis.get("bytes_accessed") or 0.0),
        "ici_seconds": float(cost.get("ici_seconds") or 0.0),
        "host_syncs": int(host.get("syncs") or 0),
        "prefixes": [p for p in prefixes if isinstance(p, dict)],
    }


def _record_from_qm(plan, qm) -> Dict[str, Any]:
    """Normalized workload record straight off a completed QueryMetrics
    (no to_dict round-trip on the hot completion path)."""
    from .profile import cost_block
    cb = cost_block(qm)
    steps = [{
        "kind": str(s.kind),
        "seconds": float(getattr(s, "seconds", -1.0)),
        "rows_in": int(getattr(s, "rows_in", -1)),
        "rows_out": int(getattr(s, "rows_out", -1)),
    } for s in (qm.steps or []) if getattr(s, "kind", None)]
    return {
        "fingerprint": str(qm.fingerprint or ""),
        "mode": str(qm.mode or "?"),
        "total_seconds": max(float(qm.total_seconds), 0.0),
        "execute_seconds": max(float(qm.execute_seconds), 0.0),
        "input_rows": int(qm.input_rows or 0),
        "steps": steps,
        "bytes_accessed": float(
            (cb.get("analysis") or {}).get("bytes_accessed") or 0.0),
        "ici_seconds": float(cb.get("ici_seconds") or 0.0),
        "host_syncs": int(qm.host_syncs or 0),
        "prefixes": plan_prefixes(plan, qm),
    }


# ---------------------------------------------------------------------------
# Feeds (hot path: one env read when off; normalize + append when on)
# ---------------------------------------------------------------------------

def feed_query(plan, qm) -> List[Dict[str, Any]]:
    """One query completed: fold it into the workload window.  Called
    from ``obs.history.maybe_record`` — the one completion point that
    holds both the optimized plan and the QueryMetrics — so every
    metered run/analyze/stream/dist query lands here.  Returns the
    plan's prefix dicts so the history sink can embed them in the JSONL
    record (offline replay then shares the live hash space)."""
    if qm is None or not metrics_enabled():
        return []
    rec = _record_from_qm(plan, qm)
    with _LOCK:
        _QUERIES.append((_now(), rec))
    return rec["prefixes"]


def feed_ticket(fingerprint: str, plan) -> None:
    """One ticket submitted to the serving scheduler: its plan's prefix
    fingerprints join the window as in-flight recurrence evidence."""
    if not metrics_enabled():
        return
    fps = tuple(p["fingerprint"] for p in plan_prefixes(plan))
    with _LOCK:
        _TICKETS.append((_now(), str(fingerprint or ""), fps))


#: Semantic-cache outcome feed: event name -> count, plus per-prefix
#: hit totals and the cold-evicted prefix set that damps future
#: recommendations.  This is the loop-closing channel — the cache
#: reports what happened to materializations the advisor proposed.
_SEMANTIC_EVENTS: Dict[str, int] = {}
_SEMANTIC_HITS: Dict[str, int] = {}
_COLD_EVICTED: set = set()
_CONFIRMED_SINK = None


def feed_semantic(event: str, prefix_fp: str = "", hits: int = 0) -> None:
    """One semantic-cache/view lifecycle event (serve/semantic.py,
    views/registry.py): ``hit``, ``miss``, ``materialize``, ``evict``,
    ``view_fold``, ``view_refresh``, ``view_hit``, ``auto_view``.  An
    ``evict`` with ``hits == 0`` marks the prefix cold — future
    ``materialize_subplan`` recommendations for it are damped
    (:data:`COLD_SEVERITY_CAP`)."""
    if not metrics_enabled():
        return
    with _LOCK:
        _SEMANTIC_EVENTS[event] = _SEMANTIC_EVENTS.get(event, 0) + 1
        if prefix_fp and event == "hit":
            _SEMANTIC_HITS[prefix_fp] = \
                _SEMANTIC_HITS.get(prefix_fp, 0) + max(int(hits), 1)
        if prefix_fp and event == "evict":
            if int(hits) <= 0:
                _COLD_EVICTED.add(prefix_fp)
            else:
                _COLD_EVICTED.discard(prefix_fp)


def semantic_stats() -> Dict[str, Any]:
    """Aggregated semantic-cache outcome counts for the window —
    consumed by the ``/views`` endpoint and the semantic bench lane."""
    with _LOCK:
        return {
            "events": dict(sorted(_SEMANTIC_EVENTS.items())),
            "prefix_hits": dict(sorted(_SEMANTIC_HITS.items())),
            "cold_evicted": sorted(_COLD_EVICTED),
        }


def cold_evicted_fps() -> Tuple[str, ...]:
    """Prefixes materialized once and evicted hitless (damping input
    for :func:`recommend`)."""
    with _LOCK:
        return tuple(sorted(_COLD_EVICTED))


def set_confirmed_sink(fn) -> None:
    """Register a callback invoked by :func:`advise` with the list of
    hysteresis-*confirmed* ``materialize_subplan`` prefix fingerprints —
    the channel through which confirmed recommendations reach the
    semantic cache (and, under ``SRT_VIEWS_AUTO``, auto-register
    views).  ``None`` uninstalls.  Failures in the sink never break
    advise()."""
    global _CONFIRMED_SINK
    _CONFIRMED_SINK = fn


def reset() -> None:
    """Drop the window and advisor state (test/bench isolation)."""
    with _LOCK:
        _QUERIES.clear()
        _TICKETS.clear()
        _SEMANTIC_EVENTS.clear()
        _SEMANTIC_HITS.clear()
        _COLD_EVICTED.clear()
    _ADVISOR.reset()


# ---------------------------------------------------------------------------
# Pure derivations
# ---------------------------------------------------------------------------

def derive(records: Sequence[Dict[str, Any]],
           tickets: Sequence[Tuple[str, Tuple[str, ...]]],
           window_seconds: float, *, topk: int,
           inflight_plans: Sequence[str] = (),
           speedups: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """The workload snapshot for one window of normalized records —
    pure.  ``tickets`` are ``(plan_fp, prefix_fps)`` pairs from the
    scheduler feed; ``inflight_plans`` are the live registry's
    currently-running plan fingerprints (context only); ``speedups``
    maps kernel names to measured oracle/kernel wall ratios (the kernel
    registry's ``measured_speedups()``) — a hotspot whose kind has a
    measured kernel cites that ratio in ``projected_win_s`` instead of
    the :data:`KERNEL_SPEEDUP` prior.

    Hotspot attribution: measured step seconds are used directly;
    records without per-step measurements spread their
    ``execute_seconds`` across their steps uniformly.  Record-level
    ledger totals (bytes accessed, ICI seconds, host syncs) are
    attributed to kinds proportionally to each step's seconds share —
    an explainable estimate, cited as such.
    """
    topk = max(int(topk), 1)
    window = max(window_seconds, 1e-9)

    kinds: Dict[str, Dict[str, Any]] = {}
    per_row: Dict[str, List[float]] = {}
    overlaps: Dict[str, Dict[str, Any]] = {}
    modes: Dict[str, int] = {}
    plans = set()
    total_step_seconds = 0.0

    for rec in records:
        fp = rec.get("fingerprint") or ""
        if fp:
            plans.add(fp)
        modes[rec.get("mode", "?")] = modes.get(rec.get("mode", "?"), 0) + 1
        steps = rec.get("steps") or []
        n = len(steps)
        secs = []
        for s in steps:
            sec = float(s.get("seconds", -1.0))
            if sec < 0.0:
                sec = float(rec.get("execute_seconds") or 0.0) / max(n, 1)
            secs.append(max(sec, 0.0))
        rec_total = sum(secs)
        total_step_seconds += rec_total
        for s, sec in zip(steps, secs):
            kind = s["kind"]
            share = sec / rec_total if rec_total > 0 else 1.0 / max(n, 1)
            agg = kinds.setdefault(kind, {
                "kind": kind, "seconds": 0.0, "steps": 0, "queries": set(),
                "rows_in": 0, "rows_out": 0, "bytes": 0.0,
                "ici_seconds": 0.0, "host_syncs": 0.0,
            })
            agg["seconds"] += sec
            agg["steps"] += 1
            agg["queries"].add(fp or id(rec))
            if s.get("rows_in", -1) >= 0:
                agg["rows_in"] += int(s["rows_in"])
                agg["rows_out"] += max(int(s.get("rows_out", 0)), 0)
                measured_sec = float(s.get("seconds", -1.0))
                if measured_sec >= 0.0 and s["rows_in"] > 0:
                    per_row.setdefault(kind, []).append(
                        measured_sec / s["rows_in"])
            agg["bytes"] += share * float(rec.get("bytes_accessed") or 0.0)
            agg["ici_seconds"] += share * float(
                rec.get("ici_seconds") or 0.0)
            agg["host_syncs"] += share * float(rec.get("host_syncs") or 0)
        for p in rec.get("prefixes") or []:
            pfp = p.get("fingerprint")
            if not pfp:
                continue
            o = overlaps.setdefault(pfp, {
                "prefix_fingerprint": pfp, "depth": int(p.get("depth", 0)),
                "kinds": list(p.get("kinds") or ()), "count": 0,
                "plans": set(), "inflight": 0, "seconds_sum": 0.0,
                "measured": False, "est_result_bytes": 0,
            })
            o["count"] += 1
            if fp:
                o["plans"].add(fp)
            o["seconds_sum"] += float(p.get("seconds") or 0.0)
            o["measured"] = o["measured"] or bool(p.get("measured"))
            o["est_result_bytes"] = max(
                o["est_result_bytes"], int(p.get("est_result_bytes") or 0))

    for _plan_fp, fps in tickets:
        for pfp in fps:
            if pfp in overlaps:
                overlaps[pfp]["inflight"] += 1

    hotspots: List[Dict[str, Any]] = []
    for agg in kinds.values():
        sec = agg["seconds"]
        share = sec / total_step_seconds if total_step_seconds > 0 else 0.0
        samples = per_row.get(agg["kind"], [])
        kernel = KERNEL_FOR_KIND.get(agg["kind"])
        assumed = float((speedups or {}).get(kernel, 0.0)) or KERNEL_SPEEDUP
        assumed = max(assumed, 1.0)  # a slower kernel projects no win
        hotspots.append({
            "kind": agg["kind"],
            "seconds": round(sec, 6),
            "share": round(share, 4),
            "steps": agg["steps"],
            "queries": len(agg["queries"]),
            "rows_in": agg["rows_in"],
            "rows_out": agg["rows_out"],
            "bytes": round(agg["bytes"], 1),
            "ici_seconds": round(agg["ici_seconds"], 6),
            "host_syncs": round(agg["host_syncs"], 1),
            "per_row_p50_s": percentile(samples, 50.0),
            "per_row_p95_s": percentile(samples, 95.0),
            "assumed_speedup": round(assumed, 4),
            "projected_win_s": round(sec * (1.0 - 1.0 / assumed), 6),
        })
    hotspots.sort(key=lambda h: (-h["seconds"], h["kind"]))

    cands: List[Dict[str, Any]] = []
    for o in overlaps.values():
        mean = o["seconds_sum"] / o["count"] if o["count"] else 0.0
        cands.append({
            "prefix_fingerprint": o["prefix_fingerprint"],
            "depth": o["depth"],
            "kinds": o["kinds"],
            "count": o["count"],
            "plans": len(o["plans"]),
            "inflight": o["inflight"],
            "seconds_mean": round(mean, 6),
            "measured": o["measured"],
            "est_result_bytes": o["est_result_bytes"],
            "benefit_score": round(
                o["count"] * mean * max(o["est_result_bytes"], 1), 3),
        })
    cands = [c for c in cands if c["count"] >= OVERLAP_MIN_COUNT]
    # Nested prefixes of one chain all recur together; among candidates
    # covering the same query set at the same frequency, keep only the
    # highest-benefit depth so the report names each chain once.
    best: Dict[Tuple[int, int], Dict[str, Any]] = {}
    for c in cands:
        key = (c["count"], c["plans"])
        cur = best.get(key)
        if cur is None or (c["benefit_score"], c["depth"]) \
                > (cur["benefit_score"], cur["depth"]):
            best[key] = c
    ranked = sorted(best.values(),
                    key=lambda c: (-c["benefit_score"], -c["count"],
                                   c["prefix_fingerprint"]))

    return {
        "window_seconds": window,
        "queries": len(records),
        "plans": len(plans),
        "modes": dict(sorted(modes.items())),
        "step_seconds": round(total_step_seconds, 6),
        "step_kinds": len(kinds),
        "hotspots": hotspots[:topk],
        "overlaps": ranked[:topk],
        "tickets": len(tickets),
        "inflight_plans": sorted(set(fp for fp in inflight_plans if fp)),
    }


def recommend(snap: Dict[str, Any],
              cold_evicted: Sequence[str] = ()) -> List[Dict[str, Any]]:
    """Ranked candidate actions for one workload snapshot — pure.

    ``pallas_kernel:<kind>`` names a kernel target whose step kind
    dominates the window; ``materialize_subplan:<fp>`` names a
    recurring prefix worth a fragment cache.  Each cites its evidence,
    like the capacity advisor's candidates.  ``cold_evicted`` prefixes
    (materialized before, evicted hitless — :func:`cold_evicted_fps`)
    have their severity capped at :data:`COLD_SEVERITY_CAP`."""
    cold = set(cold_evicted)
    out: List[Dict[str, Any]] = []
    for rank, h in enumerate(snap.get("hotspots") or []):
        if h["share"] < HOTSPOT_MIN_SHARE \
                or h["seconds"] < HOTSPOT_MIN_SECONDS:
            continue
        severity = 80 if h["share"] >= 0.5 else \
            (65 if h["share"] >= 0.35 else 50)
        out.append({
            "action": f"pallas_kernel:{h['kind']}",
            "severity": severity,
            "reason": f"step kind {h['kind']!r} holds "
                      f"{h['share']:.0%} of attributed step seconds in "
                      f"the window — the top Pallas kernel target "
                      f"(rank {rank + 1})",
            "evidence": {
                "seconds": h["seconds"],
                "share": h["share"],
                "queries": h["queries"],
                "bytes": h["bytes"],
                "ici_seconds": h["ici_seconds"],
                "host_syncs": h["host_syncs"],
                "per_row_p95_s": h["per_row_p95_s"],
                "projected_win_s": h["projected_win_s"],
            },
        })
    for o in snap.get("overlaps") or []:
        if o["count"] < OVERLAP_MIN_COUNT or o["seconds_mean"] <= 0.0:
            continue
        severity = 75 if (o["count"] >= 4 and o["measured"]) else 55
        damped = o["prefix_fingerprint"] in cold
        if damped:
            severity = min(severity, COLD_SEVERITY_CAP)
        reason = (f"subplan prefix "
                  f"{' > '.join(o['kinds'])} recurred "
                  f"{o['count']}x across {o['plans']} plan(s) — "
                  f"materializing it would amortize "
                  f"{o['seconds_mean']:.4f}s per recurrence")
        if damped:
            reason += (" (damped: a previous materialization was "
                       "evicted without a hit)")
        out.append({
            "action": f"materialize_subplan:{o['prefix_fingerprint']}",
            "severity": severity,
            "reason": reason,
            "evidence": {
                "prefix_fingerprint": o["prefix_fingerprint"],
                "depth": o["depth"],
                "count": o["count"],
                "plans": o["plans"],
                "inflight": o["inflight"],
                "seconds_mean": o["seconds_mean"],
                "measured": o["measured"],
                "est_result_bytes": o["est_result_bytes"],
                "benefit_score": o["benefit_score"],
            },
        })
    out.sort(key=lambda r: (-r["severity"], r["action"]))
    return out


def verdict_for(recommendations: List[Dict[str, Any]]) -> str:
    """One-word operator verdict for a workload recommendation set."""
    if not recommendations:
        return "quiet"
    top = recommendations[0]["severity"]
    if top >= 75:
        return "actionable"
    if top >= 50:
        return "suggestive"
    return "informational"


# ---------------------------------------------------------------------------
# Ambient wrappers (knobs + the live window; thin over the pure core)
# ---------------------------------------------------------------------------

_ADVISOR = Advisor()


def window_records(w0: float, w1: float
                   ) -> Tuple[List[Dict[str, Any]],
                              List[Tuple[str, Tuple[str, ...]]]]:
    """Copies of the live window's query records and ticket feeds whose
    timestamps fall in ``[w0, w1]``."""
    with _LOCK:
        recs = [r for t, r in _QUERIES if w0 <= t <= w1]
        tks = [(fp, fps) for t, fp, fps in _TICKETS if w0 <= t <= w1]
    return recs, tks


def _live_inflight_plans() -> List[str]:
    """Plan fingerprints currently running per the live registry —
    best-effort context for the snapshot."""
    try:
        from . import live
        snap = live.snapshot_all()
        return [q.get("fingerprint") or ""
                for q in snap.get("in_flight", [])]
    except Exception:
        return []


def _measured_speedups() -> Dict[str, float]:
    """Measured per-kernel speedups from the kernel registry —
    best-effort (an import problem must not break the snapshot)."""
    try:
        from ..kernels import registry
        return registry.measured_speedups()
    except Exception:
        return {}


def kernels_block() -> Dict[str, Any]:
    """The ``kernels`` block of a ``/workload`` payload: the kernel
    registry's enabled/quarantined sets and per-kernel counters plus
    measured speedups — never raises."""
    try:
        from ..kernels import registry
        return registry.stats()
    except Exception as exc:  # pragma: no cover - defensive
        return {"enabled": [], "quarantined": [],
                "per_kernel": {}, "error": type(exc).__name__}


def snapshot(window_s: Optional[float] = None) -> Dict[str, Any]:
    """Workload observables for the trailing window (knobs ambient)."""
    from ..config import workload_topk, workload_window_s
    window = workload_window_s() if window_s is None else float(window_s)
    w1 = _now()
    recs, tks = window_records(w1 - window, w1)
    return derive(recs, tks, window, topk=workload_topk(),
                  inflight_plans=_live_inflight_plans(),
                  speedups=_measured_speedups())


def advise(window_s: Optional[float] = None,
           advisor: Optional[Advisor] = None) -> Dict[str, Any]:
    """One workload-advisor evaluation over the live window —
    ``candidates`` are this window's raw proposals,
    ``recommendations`` the hysteresis-stable set (the module-level
    advisor by default, so repeated ``/workload`` fetches confirm and
    clear actions; ``/metrics`` scrapes never call this)."""
    snap = snapshot(window_s)
    candidates = recommend(snap, cold_evicted=cold_evicted_fps())
    adv = _ADVISOR if advisor is None else advisor
    recs = adv.observe(candidates)
    sink = _CONFIRMED_SINK
    if sink is not None:
        confirmed = [r["action"].split(":", 1)[1] for r in recs
                     if r["action"].startswith("materialize_subplan:")]
        if confirmed:
            try:
                sink(confirmed)
            except Exception:  # a broken sink must not break advise()
                pass
    return {
        "snapshot": snap,
        "candidates": candidates,
        "recommendations": recs,
        "kernels": kernels_block(),
        "verdict": verdict_for(recs if recs else candidates),
    }


def bundle_block() -> Dict[str, Any]:
    """Workload block for a postmortem bundle — never raises, like
    capacity.bundle_block (a broken miner must not block an incident
    bundle)."""
    try:
        payload = advise()
        return {
            "snapshot": payload["snapshot"],
            "recommendations": payload["recommendations"]
            or payload["candidates"],
            "verdict": payload["verdict"],
        }
    except Exception as exc:  # pragma: no cover - defensive
        return {"snapshot": None, "recommendations": [],
                "verdict": f"unavailable: {type(exc).__name__}"}


def validate_payload(payload: Dict[str, Any],
                     schema: Dict[str, Any]) -> List[str]:
    """Check a ``/workload`` payload (also the shape ``obs workload
    --json`` prints for every source) against the golden-pinned schema
    (tests/golden/workload_endpoint_schema.json): exact top-level and
    snapshot key sets, exact per-entry key sets for hotspots, overlap
    candidates, and recommendations, a pinned verdict vocabulary, and a
    pinned action namespace.  Returns human-readable problems (empty =
    valid); shared by the test suite and the CI workload lane."""
    errors: List[str] = []
    if not isinstance(payload, dict):
        return ["payload is not an object"]
    if sorted(payload) != sorted(schema["top_level_keys"]):
        return [f"top-level keys {sorted(payload)} != "
                f"{sorted(schema['top_level_keys'])}"]
    snap = payload["snapshot"]
    if not isinstance(snap, dict):
        return ["'snapshot' is not an object"]
    if sorted(snap) != sorted(schema["snapshot_keys"]):
        errors.append(f"snapshot keys {sorted(snap)} != "
                      f"{sorted(schema['snapshot_keys'])}")
    for i, h in enumerate(snap.get("hotspots") or []):
        if not isinstance(h, dict) \
                or sorted(h) != sorted(schema["hotspot_keys"]):
            errors.append(f"hotspots[{i}] keys != {schema['hotspot_keys']}")
    for i, o in enumerate(snap.get("overlaps") or []):
        if not isinstance(o, dict) \
                or sorted(o) != sorted(schema["overlap_keys"]):
            errors.append(f"overlaps[{i}] keys != {schema['overlap_keys']}")
    for group in ("candidates", "recommendations"):
        for i, r in enumerate(payload.get(group) or []):
            if not isinstance(r, dict) \
                    or sorted(r) != sorted(schema["recommendation_keys"]):
                errors.append(f"{group}[{i}] keys != "
                              f"{schema['recommendation_keys']}")
                continue
            action = str(r.get("action") or "")
            if action.split(":", 1)[0] not in schema["actions"]:
                errors.append(f"{group}[{i}] action {action!r} outside "
                              f"the pinned namespace {schema['actions']}")
    kern = payload.get("kernels")
    if not isinstance(kern, dict) \
            or sorted(kern) != sorted(schema["kernels_keys"]):
        errors.append(f"'kernels' keys != {schema['kernels_keys']}")
    if payload.get("verdict") not in schema["verdicts"]:
        errors.append(f"verdict {payload.get('verdict')!r} not in "
                      f"{schema['verdicts']}")
    return errors


# ---------------------------------------------------------------------------
# Offline: replay metrics-history records through the same pure core
# ---------------------------------------------------------------------------

def records_from_history(records: Sequence[Dict[str, Any]]
                         ) -> Tuple[List[Dict[str, Any]], float]:
    """Normalize history JSONL records (oldest first) for
    :func:`derive`.  Returns ``(records, window_seconds)`` — the replay
    is serialized like capacity.events_from_history: the synthetic
    window is the summed total_seconds, so hotspot shares read as "of
    serialized runtime"."""
    out: List[Dict[str, Any]] = []
    cursor = 0.0
    for rec in records:
        norm = record_from_history(rec)
        if norm is None:
            continue
        out.append(norm)
        cursor += norm["total_seconds"]
    return out, max(cursor, 1e-9)
