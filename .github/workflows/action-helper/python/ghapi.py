"""Minimal GitHub REST helper for the repo-automation bots.

Reference analog: .github/workflows/action-helper/python/utils.py (a
requests-based PullRequest class used by the auto-merge / submodule-sync /
cleanup bots).  This one is stdlib-only (urllib) so the container action
needs no third-party installs, and the decision logic is factored into
pure functions (`pick_existing_pr`, `should_auto_merge`, `strtobool`) so the
test suite can exercise bot behavior offline (tests/test_automation.py).
"""

from __future__ import annotations

import argparse
import json
import os
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

API_ROOT = os.environ.get("GITHUB_API_URL", "https://api.github.com")


def strtobool(val: str) -> bool:
    """Parse truthy/falsy strings ("true"/"false" from workflow inputs)."""
    v = str(val).strip().lower()
    if v in ("y", "yes", "t", "true", "on", "1"):
        return True
    if v in ("n", "no", "f", "false", "off", "0"):
        return False
    raise ValueError(f"invalid truth value {val!r}")


class EnvDefault(argparse.Action):
    """argparse action that defaults to an environment variable."""

    def __init__(self, env, required=True, default=None, **kwargs):
        if default is None and env in os.environ:
            default = os.environ[env]
        if default is not None:
            required = False
        super().__init__(default=default, required=required, **kwargs)
        self.env = env

    def __call__(self, parser, namespace, values, option_string=None):
        setattr(namespace, self.dest, values)


def pick_existing_pr(prs: List[Dict[str, Any]], head_ref: str,
                     base_ref: str) -> Optional[Dict[str, Any]]:
    """Choose the open PR matching head/base, if any (pure function)."""
    for pr in prs:
        if (pr.get("head", {}).get("ref") == head_ref
                and pr.get("base", {}).get("ref") == base_ref
                and pr.get("state") == "open"):
            return pr
    return None


def should_auto_merge(passed: bool, local_sha: str, remote_sha: str) -> bool:
    """Merge only when tests passed AND the pushed head still matches what
    was tested (reference submodule-sync gate: python/submodule-sync:72-78)."""
    return bool(passed) and bool(local_sha) and local_sha == remote_sha


class Repo:
    """Thin authenticated client bound to one repository."""

    def __init__(self, repo: str, token: str):
        self.repo = repo
        self.token = token

    # -- transport -----------------------------------------------------------
    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        url = f"{API_ROOT}/repos/{self.repo}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method, headers={
            "Authorization": f"Bearer {self.token}",
            "Accept": "application/vnd.github+json",
            "Content-Type": "application/json",
        })
        try:
            with urllib.request.urlopen(req) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise RuntimeError(
                f"{method} {path} -> HTTP {e.code}: {detail}") from None
        return json.loads(payload) if payload else None

    def _paginate(self, path: str) -> List[Dict[str, Any]]:
        """Fetch every page (GitHub clamps per_page at 100); stops on the
        first short page."""
        sep = "&" if "?" in path else "?"
        out: List[Dict[str, Any]] = []
        page = 1
        while True:
            batch = self._request(
                "GET", f"{path}{sep}per_page=100&page={page}") or []
            out.extend(batch)
            if len(batch) < 100:
                return out
            page += 1

    # -- pull requests -------------------------------------------------------
    def open_prs(self, head_ref: Optional[str] = None) -> List[Dict[str, Any]]:
        prs = self._paginate("/pulls?state=open")
        if head_ref:
            prs = [p for p in prs if p["head"]["ref"] == head_ref]
        return prs

    def create_pr(self, title: str, head: str, base: str,
                  body: str = "") -> Dict[str, Any]:
        return self._request("POST", "/pulls", {
            "title": title, "head": head, "base": base, "body": body,
            "maintainer_can_modify": True})

    def ensure_pr(self, title: str, head: str, base: str,
                  body: str = "") -> Dict[str, Any]:
        existing = pick_existing_pr(self.open_prs(), head, base)
        return existing if existing else self.create_pr(title, head, base, body)

    def comment(self, number: int, text: str) -> None:
        self._request("POST", f"/issues/{number}/comments", {"body": text})

    def merge_pr(self, number: int, method: str = "squash") -> bool:
        try:
            out = self._request("PUT", f"/pulls/{number}/merge",
                                {"merge_method": method})
            return bool(out and out.get("merged"))
        except RuntimeError as e:
            print(f"merge failed: {e}")
            return False

    def head_sha(self, branch: str) -> str:
        out = self._request("GET", f"/git/ref/heads/{branch}")
        return out["object"]["sha"]

    def delete_branch(self, branch: str) -> None:
        self._request("DELETE", f"/git/refs/heads/{branch}")

    def branches(self, prefix: str = "") -> List[str]:
        out = self._paginate("/branches")
        return [b["name"] for b in out if b["name"].startswith(prefix)]
